"""Serving load balancer (serving/lb.py): least-loaded dispatch, health,
failover, drain on scale-down.

The reference's serving scale-out was a TF-Serving Deployment behind a
Service with kube-proxy connection spreading
(reference testing/test_tf_serving.py:60-100); the platform replaces that
with an L7 balancer aware of per-request load and streams.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.serving.lb import ServingLBServer, ServingLoadBalancer
from kubeflow_tpu.webapps.router import (
    JsonHttpServer,
    NdjsonStream,
    Request,
    RestError,
    Router,
)


class StubBackend:
    """Looks like serving.server to the LB: /v1/generate, /v1/models,
    /healthz. Generation echoes which backend served it; an Event can
    hold responses open so tests can pin in-flight load."""

    def __init__(self, name: str):
        self.name = name
        self.requests = 0
        self.hold = threading.Event()
        self.hold.set()                 # open = respond immediately
        self.ok = True
        r = Router()
        r.post("/v1/generate", self._generate)
        r.get("/v1/models", lambda q: {"models": [{"name": self.name}]})
        r.get("/healthz", self._healthz)
        self._srv = JsonHttpServer(r, port=0).start()
        self.addr = f"127.0.0.1:{self._srv.port}"

    def _healthz(self, q: Request):
        return {"ok": True} if self.ok else (503, {"ok": False})

    def _generate(self, q: Request):
        self.requests += 1
        if not q.body.get("tokens"):
            raise RestError(400, "body.tokens must be a list of ints")
        self.hold.wait(10)
        if q.body.get("stream"):
            def chunks():
                yield {"tokens": [1, 2], "backend": self.name}
                self.hold.wait(10)
                yield {"done": True, "backend": self.name}
            return NdjsonStream(chunks())
        return {"tokens": [1, 2, 3], "backend": self.name}

    def stop(self):
        self._srv.stop()


@pytest.fixture()
def backends():
    b = [StubBackend("b0"), StubBackend("b1")]
    yield b
    for x in b:
        x.stop()


def _post(url, body, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    return urllib.request.urlopen(req, timeout=timeout)


class TestDispatch:
    def test_least_loaded_dispatch(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        url = f"http://127.0.0.1:{srv.port}/v1/generate"
        try:
            # hold b-something busy with a pinned request, then send
            # another: it must go to the idle backend
            b0.hold.clear()
            b1.hold.clear()
            first = threading.Thread(
                target=lambda: _post(url, {"tokens": [1]}).read())
            first.start()
            deadline = time.time() + 5
            while not (b0.requests or b1.requests):
                assert time.time() < deadline
                time.sleep(0.01)
            busy, idle = (b0, b1) if b0.requests else (b1, b0)
            idle.hold.set()
            out = json.load(_post(url, {"tokens": [1]}))
            assert out["backend"] == idle.name
            busy.hold.set()
            first.join(timeout=5)
            assert busy.requests == 1 and idle.requests == 1
        finally:
            b0.hold.set()
            b1.hold.set()
            srv.stop()

    def test_application_errors_relay_untouched(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": []})
            assert ei.value.code == 400
            assert "tokens" in json.load(ei.value)["error"]
            # a 400 is the backend SPEAKING http — it must stay healthy
            assert all(b["healthy"] for b in lb.backends())
        finally:
            srv.stop()

    def test_failover_to_live_backend(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            b0.stop()   # dead socket: connection refused
            out = json.load(_post(
                f"http://127.0.0.1:{srv.port}/v1/generate", {"tokens": [1]}))
            assert out["backend"] == "b1"
            snap = {b["addr"]: b for b in lb.backends()}
            assert snap[b0.addr]["healthy"] is False
            assert snap[b1.addr]["healthy"] is True
        finally:
            srv.stop()

    def test_all_dead_is_502_then_503(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            b0.stop()
            b1.stop()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": [1]})
            assert ei.value.code == 502
            # both now marked unhealthy -> no candidates -> 503
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": [1]})
            assert ei.value.code == 503
        finally:
            srv.stop()

    def test_streaming_relay(self, backends):
        b0, _ = backends
        lb = ServingLoadBalancer([b0.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            resp = _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                         {"tokens": [1], "stream": True})
            chunks = [json.loads(l) for l in resp if l.strip()]
            assert chunks[0]["tokens"] == [1, 2]
            assert chunks[-1]["done"] is True
        finally:
            srv.stop()


class TestHealthAndDrain:
    def test_health_check_recovers_backend(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        b0.ok = False
        assert lb.health_check() == 1
        snap = {b["addr"]: b for b in lb.backends()}
        assert snap[b0.addr]["healthy"] is False
        b0.ok = True
        assert lb.health_check() == 2
        assert all(b["healthy"] for b in lb.backends())

    def test_drain_holds_until_in_flight_zero(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        url = f"http://127.0.0.1:{srv.port}/v1/generate"
        try:
            b0.hold.clear()
            b1.hold.clear()
            t = threading.Thread(
                target=lambda: _post(url, {"tokens": [1]}).read())
            t.start()
            deadline = time.time() + 5
            while not (b0.requests or b1.requests):
                assert time.time() < deadline
                time.sleep(0.01)
            busy = b0 if b0.requests else b1
            other = b1 if busy is b0 else b0
            # scale down to just the idle backend: busy one must DRAIN,
            # not vanish (its request is still in flight)
            lb.set_backends([other.addr])
            snap = {b["addr"]: b for b in lb.backends()}
            assert snap[busy.addr]["draining"] is True
            # new requests only go to the survivor
            other.hold.set()
            out = json.load(_post(url, {"tokens": [1]}))
            assert out["backend"] == other.name
            # in-flight completes -> drained backend is dropped
            busy.hold.set()
            t.join(timeout=5)
            deadline = time.time() + 5
            while any(b["addr"] == busy.addr for b in lb.backends()):
                assert time.time() < deadline
                time.sleep(0.01)
        finally:
            b0.hold.set()
            b1.hold.set()
            srv.stop()

    def test_set_backends_revert_undrains(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        url = f"http://127.0.0.1:{srv.port}/v1/generate"
        try:
            b0.hold.clear()
            t = threading.Thread(
                target=lambda: _post(url, {"tokens": [1]}).read())
            t.start()
            deadline = time.time() + 5
            while not (b0.requests or b1.requests):
                assert time.time() < deadline
                time.sleep(0.01)
            busy = b0 if b0.requests else b1
            lb.set_backends([b1.addr] if busy is b0 else [b0.addr])
            lb.set_backends([b0.addr, b1.addr])   # scale-down reverted
            snap = {b["addr"]: b for b in lb.backends()}
            assert not any(b["draining"] for b in snap.values())
            b0.hold.set()
            t.join(timeout=5)
        finally:
            b0.hold.set()
            srv.stop()

    def test_healthz_aggregates(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz"))
            assert body["ok"] is True
            assert len(body["backends"]) == 2
            lb.set_backends([])
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/healthz")
            assert ei.value.code == 503
        finally:
            srv.stop()


class TestChaosFlapsAndRetryAfter:
    def test_flapped_backend_is_invisible_to_clients(self, backends):
        """BackendFlapper takes a backend down between health checks; every
        request still succeeds via the survivor — zero client-visible
        errors — and health_check() recovers the flapped backend."""
        from kubeflow_tpu.chaos import BackendFlapper

        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        url = f"http://127.0.0.1:{srv.port}/v1/generate"
        try:
            flapper = BackendFlapper(lb, seed=5)
            served = []
            for i in range(12):
                if i % 4 == 1:
                    assert flapper.flap() is not None
                if i % 4 == 3:
                    assert flapper.heal() == 2   # /healthz still answers
                out = json.load(_post(url, {"tokens": [1]}))
                served.append(out["backend"])
            assert len(served) == 12             # no request ever failed
            assert {"b0", "b1"} >= set(served)
        finally:
            srv.stop()

    def test_flapper_keeps_last_backend(self, backends):
        from kubeflow_tpu.chaos import BackendFlapper

        b0, _ = backends
        lb = ServingLoadBalancer([b0.addr])
        flapper = BackendFlapper(lb, seed=0)
        assert flapper.flap() is None            # refuses a full outage
        assert flapper.flap(keep_one=False) == b0.addr

    def test_503_carries_retry_after(self):
        """A backendless balancer tells clients when to come back instead
        of letting them hammer: Retry-After derives from the health-check
        interval."""
        lb = ServingLoadBalancer([], retry_after_s=7.3)
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": [1]})
            assert ei.value.code == 503
            assert ei.value.headers["Retry-After"] == "8"  # ceil(7.3)
        finally:
            srv.stop()

    def test_retry_after_defaults_to_sync_interval(self):
        lb = ServingLoadBalancer([])
        ServingLBServer(lb, sync_interval_s=4.0).stop()
        assert lb.retry_after_s == 4.0
        lb2 = ServingLoadBalancer([])
        assert lb2._retry_after() == "2"  # health_timeout_s fallback


class TestLBMain:
    def test_entrypoint_with_static_backends(self, backends):
        """`python -m kubeflow_tpu.serving.lb --backends ...` as a
        subprocess: the deployable form of the balancer."""
        import subprocess
        import sys
        import time as _time

        b0, b1 = backends
        proc = subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.serving.lb",
             "--host", "127.0.0.1", "--port", "0",
             "--backends", f"{b0.addr},{b1.addr}"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            # the entrypoint logs its bound port; parse it
            port = None
            deadline = _time.time() + 60
            while _time.time() < deadline and port is None:
                line = proc.stdout.readline()
                if "serving lb up" in line:
                    port = int(line.split("port=")[1].split()[0])
            assert port, "lb entrypoint never reported its port"
            out = json.load(_post(f"http://127.0.0.1:{port}/v1/generate",
                                  {"tokens": [1]}))
            assert out["backend"] in ("b0", "b1")
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz"))
            assert body["ok"] is True and len(body["backends"]) == 2
        finally:
            proc.kill()
            proc.wait(timeout=10)


class TestLBServer:
    def test_follows_serving_cr_endpoints(self, backends):
        """ServingLBServer.tick() syncs the dispatch set from the Serving
        CR's status.endpoints (what the controller maintains)."""
        from kubeflow_tpu.controlplane.api import Serving, ServingSpec
        from kubeflow_tpu.controlplane.api.meta import ObjectMeta
        from kubeflow_tpu.controlplane.runtime.apiserver import (
            InMemoryApiServer,
        )

        b0, b1 = backends
        api = InMemoryApiServer()
        sv = Serving(metadata=ObjectMeta(name="llm", namespace="team-a"),
                     spec=ServingSpec(model="llama-tiny"))
        api.create(sv)
        sv = api.get("Serving", "llm", "team-a")
        sv.status.endpoints = [b0.addr, b1.addr]
        api.update_status(sv)

        lb = ServingLoadBalancer()
        srv = ServingLBServer(lb, api=api, namespace="team-a", name="llm")
        srv.tick()
        assert {b["addr"] for b in lb.backends()} == {b0.addr, b1.addr}
        # replica leaves status.endpoints (controller drain) -> LB drains
        sv = api.get("Serving", "llm", "team-a")
        sv.status.endpoints = [b0.addr]
        api.update_status(sv)
        srv.tick()
        assert {b["addr"] for b in lb.backends()} == {b0.addr}
        srv.stop()
