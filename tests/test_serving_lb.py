"""Serving load balancer (serving/lb.py): least-loaded dispatch, health,
failover, drain on scale-down.

The reference's serving scale-out was a TF-Serving Deployment behind a
Service with kube-proxy connection spreading
(reference testing/test_tf_serving.py:60-100); the platform replaces that
with an L7 balancer aware of per-request load and streams.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.serving.lb import ServingLBServer, ServingLoadBalancer
from kubeflow_tpu.webapps.router import (
    JsonHttpServer,
    NdjsonStream,
    Request,
    RestError,
    Router,
)


class StubBackend:
    """Looks like serving.server to the LB: /v1/generate, /v1/models,
    /healthz. Generation echoes which backend served it; an Event can
    hold responses open so tests can pin in-flight load."""

    def __init__(self, name: str):
        self.name = name
        self.requests = 0
        self.hold = threading.Event()
        self.hold.set()                 # open = respond immediately
        self.ok = True
        r = Router()
        r.post("/v1/generate", self._generate)
        r.get("/v1/models", lambda q: {"models": [{"name": self.name}]})
        r.get("/healthz", self._healthz)
        self._srv = JsonHttpServer(r, port=0).start()
        self.addr = f"127.0.0.1:{self._srv.port}"

    def _healthz(self, q: Request):
        return {"ok": True} if self.ok else (503, {"ok": False})

    def _generate(self, q: Request):
        self.requests += 1
        if not q.body.get("tokens"):
            raise RestError(400, "body.tokens must be a list of ints")
        self.hold.wait(10)
        if q.body.get("stream"):
            def chunks():
                yield {"tokens": [1, 2], "backend": self.name}
                self.hold.wait(10)
                yield {"done": True, "backend": self.name}
            return NdjsonStream(chunks())
        return {"tokens": [1, 2, 3], "backend": self.name}

    def stop(self):
        self._srv.stop()


@pytest.fixture()
def backends():
    b = [StubBackend("b0"), StubBackend("b1")]
    yield b
    for x in b:
        x.stop()


def _post(url, body, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    return urllib.request.urlopen(req, timeout=timeout)


class TestDispatch:
    def test_least_loaded_dispatch(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        url = f"http://127.0.0.1:{srv.port}/v1/generate"
        try:
            # hold b-something busy with a pinned request, then send
            # another: it must go to the idle backend
            b0.hold.clear()
            b1.hold.clear()
            first = threading.Thread(
                target=lambda: _post(url, {"tokens": [1]}).read())
            first.start()
            deadline = time.time() + 5
            while not (b0.requests or b1.requests):
                assert time.time() < deadline
                time.sleep(0.01)
            busy, idle = (b0, b1) if b0.requests else (b1, b0)
            idle.hold.set()
            out = json.load(_post(url, {"tokens": [1]}))
            assert out["backend"] == idle.name
            busy.hold.set()
            first.join(timeout=5)
            assert busy.requests == 1 and idle.requests == 1
        finally:
            b0.hold.set()
            b1.hold.set()
            srv.stop()

    def test_application_errors_relay_untouched(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": []})
            assert ei.value.code == 400
            assert "tokens" in json.load(ei.value)["error"]
            # a 400 is the backend SPEAKING http — it must stay healthy
            assert all(b["healthy"] for b in lb.backends())
        finally:
            srv.stop()

    def test_failover_to_live_backend(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            b0.stop()   # dead socket: connection refused
            out = json.load(_post(
                f"http://127.0.0.1:{srv.port}/v1/generate", {"tokens": [1]}))
            assert out["backend"] == "b1"
            snap = {b["addr"]: b for b in lb.backends()}
            assert snap[b0.addr]["healthy"] is False
            assert snap[b1.addr]["healthy"] is True
        finally:
            srv.stop()

    def test_all_dead_is_502_then_503(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            b0.stop()
            b1.stop()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": [1]})
            assert ei.value.code == 502
            # both now marked unhealthy -> no candidates -> 503
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": [1]})
            assert ei.value.code == 503
        finally:
            srv.stop()

    def test_streaming_relay(self, backends):
        b0, _ = backends
        lb = ServingLoadBalancer([b0.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            resp = _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                         {"tokens": [1], "stream": True})
            chunks = [json.loads(l) for l in resp if l.strip()]
            assert chunks[0]["tokens"] == [1, 2]
            assert chunks[-1]["done"] is True
        finally:
            srv.stop()


class TestHealthAndDrain:
    def test_health_check_recovers_backend(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        b0.ok = False
        assert lb.health_check() == 1
        snap = {b["addr"]: b for b in lb.backends()}
        assert snap[b0.addr]["healthy"] is False
        b0.ok = True
        assert lb.health_check() == 2
        assert all(b["healthy"] for b in lb.backends())

    def test_drain_holds_until_in_flight_zero(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        url = f"http://127.0.0.1:{srv.port}/v1/generate"
        try:
            b0.hold.clear()
            b1.hold.clear()
            t = threading.Thread(
                target=lambda: _post(url, {"tokens": [1]}).read())
            t.start()
            deadline = time.time() + 5
            while not (b0.requests or b1.requests):
                assert time.time() < deadline
                time.sleep(0.01)
            busy = b0 if b0.requests else b1
            other = b1 if busy is b0 else b0
            # scale down to just the idle backend: busy one must DRAIN,
            # not vanish (its request is still in flight)
            lb.set_backends([other.addr])
            snap = {b["addr"]: b for b in lb.backends()}
            assert snap[busy.addr]["draining"] is True
            # new requests only go to the survivor
            other.hold.set()
            out = json.load(_post(url, {"tokens": [1]}))
            assert out["backend"] == other.name
            # in-flight completes -> drained backend is dropped
            busy.hold.set()
            t.join(timeout=5)
            deadline = time.time() + 5
            while any(b["addr"] == busy.addr for b in lb.backends()):
                assert time.time() < deadline
                time.sleep(0.01)
        finally:
            b0.hold.set()
            b1.hold.set()
            srv.stop()

    def test_set_backends_revert_undrains(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        url = f"http://127.0.0.1:{srv.port}/v1/generate"
        try:
            b0.hold.clear()
            t = threading.Thread(
                target=lambda: _post(url, {"tokens": [1]}).read())
            t.start()
            deadline = time.time() + 5
            while not (b0.requests or b1.requests):
                assert time.time() < deadline
                time.sleep(0.01)
            busy = b0 if b0.requests else b1
            lb.set_backends([b1.addr] if busy is b0 else [b0.addr])
            lb.set_backends([b0.addr, b1.addr])   # scale-down reverted
            snap = {b["addr"]: b for b in lb.backends()}
            assert not any(b["draining"] for b in snap.values())
            b0.hold.set()
            t.join(timeout=5)
        finally:
            b0.hold.set()
            srv.stop()

    def test_healthz_aggregates(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz"))
            assert body["ok"] is True
            assert len(body["backends"]) == 2
            lb.set_backends([])
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/healthz")
            assert ei.value.code == 503
        finally:
            srv.stop()


class TestChaosFlapsAndRetryAfter:
    def test_flapped_backend_is_invisible_to_clients(self, backends):
        """BackendFlapper takes a backend down between health checks; every
        request still succeeds via the survivor — zero client-visible
        errors — and health_check() recovers the flapped backend."""
        from kubeflow_tpu.chaos import BackendFlapper

        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        url = f"http://127.0.0.1:{srv.port}/v1/generate"
        try:
            flapper = BackendFlapper(lb, seed=5)
            served = []
            for i in range(12):
                if i % 4 == 1:
                    assert flapper.flap() is not None
                if i % 4 == 3:
                    assert flapper.heal() == 2   # /healthz still answers
                out = json.load(_post(url, {"tokens": [1]}))
                served.append(out["backend"])
            assert len(served) == 12             # no request ever failed
            assert {"b0", "b1"} >= set(served)
        finally:
            srv.stop()

    def test_flapper_keeps_last_backend(self, backends):
        from kubeflow_tpu.chaos import BackendFlapper

        b0, _ = backends
        lb = ServingLoadBalancer([b0.addr])
        flapper = BackendFlapper(lb, seed=0)
        assert flapper.flap() is None            # refuses a full outage
        assert flapper.flap(keep_one=False) == b0.addr

    def test_503_carries_retry_after(self):
        """A backendless balancer tells clients when to come back instead
        of letting them hammer: Retry-After derives from the health-check
        interval."""
        lb = ServingLoadBalancer([], retry_after_s=7.3)
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": [1]})
            assert ei.value.code == 503
            assert ei.value.headers["Retry-After"] == "8"  # ceil(7.3)
        finally:
            srv.stop()

    def test_retry_after_defaults_to_sync_interval(self):
        lb = ServingLoadBalancer([])
        ServingLBServer(lb, sync_interval_s=4.0).stop()
        assert lb.retry_after_s == 4.0
        lb2 = ServingLoadBalancer([])
        assert lb2._retry_after() == "2"  # health_timeout_s fallback


class TestLBMain:
    def test_entrypoint_with_static_backends(self, backends):
        """`python -m kubeflow_tpu.serving.lb --backends ...` as a
        subprocess: the deployable form of the balancer."""
        import subprocess
        import sys
        import time as _time

        b0, b1 = backends
        proc = subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.serving.lb",
             "--host", "127.0.0.1", "--port", "0",
             "--backends", f"{b0.addr},{b1.addr}"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            # the entrypoint logs its bound port; parse it
            port = None
            deadline = _time.time() + 60
            while _time.time() < deadline and port is None:
                line = proc.stdout.readline()
                if "serving lb up" in line:
                    port = int(line.split("port=")[1].split()[0])
            assert port, "lb entrypoint never reported its port"
            out = json.load(_post(f"http://127.0.0.1:{port}/v1/generate",
                                  {"tokens": [1]}))
            assert out["backend"] in ("b0", "b1")
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz"))
            assert body["ok"] is True and len(body["backends"]) == 2
        finally:
            proc.kill()
            proc.wait(timeout=10)


class TestLBServer:
    def test_follows_serving_cr_endpoints(self, backends):
        """ServingLBServer.tick() syncs the dispatch set from the Serving
        CR's status.endpoints (what the controller maintains)."""
        from kubeflow_tpu.controlplane.api import Serving, ServingSpec
        from kubeflow_tpu.controlplane.api.meta import ObjectMeta
        from kubeflow_tpu.controlplane.runtime.apiserver import (
            InMemoryApiServer,
        )

        b0, b1 = backends
        api = InMemoryApiServer()
        sv = Serving(metadata=ObjectMeta(name="llm", namespace="team-a"),
                     spec=ServingSpec(model="llama-tiny"))
        api.create(sv)
        sv = api.get("Serving", "llm", "team-a")
        sv.status.endpoints = [b0.addr, b1.addr]
        api.update_status(sv)

        lb = ServingLoadBalancer()
        srv = ServingLBServer(lb, api=api, namespace="team-a", name="llm")
        srv.tick()
        assert {b["addr"] for b in lb.backends()} == {b0.addr, b1.addr}
        # replica leaves status.endpoints (controller drain) -> LB drains
        sv = api.get("Serving", "llm", "team-a")
        sv.status.endpoints = [b0.addr]
        api.update_status(sv)
        srv.tick()
        assert {b["addr"] for b in lb.backends()} == {b0.addr}
        srv.stop()


class LoadStubBackend(StubBackend):
    """StubBackend whose /healthz carries a controllable engine load
    snapshot (the ServingEngine.load shape) — the input to queue-aware
    dispatch, watermark shedding, and the autoscaler scrape."""

    def __init__(self, name: str, **load):
        self.load = {
            "queued": 0, "active_slots": 0, "free_slots": 2,
            "max_batch": 2, "max_queue": 4, "shed_total": 0,
            "p50_queue_wait_s": 0.0, "p95_queue_wait_s": 0.0, **load,
        }
        super().__init__(name)

    def _healthz(self, q: Request):
        if not self.ok:
            return (503, {"ok": False})
        return {"ok": True, "load": dict(self.load)}


@pytest.fixture()
def load_backends():
    b = [LoadStubBackend("b0"), LoadStubBackend("b1")]
    yield b
    for x in b:
        x.stop()


class TestQueueAwareDispatch:
    def test_dispatch_prefers_lower_reported_queue(self, load_backends):
        """With zero LB in-flight everywhere, the backend whose engine
        reports the shorter queue wins — depth-aware, not just
        least-in-flight."""
        b0, b1 = load_backends
        b0.load["queued"] = 5
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        assert lb.health_check() == 2
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            for _ in range(3):
                out = json.load(_post(
                    f"http://127.0.0.1:{srv.port}/v1/generate",
                    {"tokens": [1]}))
                assert out["backend"] == "b1"
        finally:
            srv.stop()

    def test_health_check_ingests_load_report(self, load_backends):
        b0, b1 = load_backends
        b0.load.update(queued=3, free_slots=1, p50_queue_wait_s=0.25)
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        lb.health_check()
        snap = {b["addr"]: b for b in lb.backends()}
        assert snap[b0.addr]["queued"] == 3
        assert snap[b0.addr]["free_slots"] == 1
        assert snap[b0.addr]["max_queue"] == 4
        assert snap[b1.addr]["queued"] == 0

    def test_sent_since_report_rebaselines_on_fresh_report(
            self, load_backends):
        """Requests dispatched between health checks count against the
        stale snapshot; a fresh report resets the correction."""
        b0, b1 = load_backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        lb.health_check()
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            for _ in range(4):
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": [1]}).read()
            assert sum(b["sent_since_report"]
                       for b in lb.backends()) == 4
            lb.health_check()
            assert all(b["sent_since_report"] == 0
                       for b in lb.backends())
        finally:
            srv.stop()


class TestLoadShedding:
    def test_sheds_503_when_all_backends_saturated(self, load_backends):
        """Every backend past its reported watermark -> 503 with a
        Retry-After at least the fleet's own queue-drain estimate."""
        b0, b1 = load_backends
        for b in (b0, b1):
            b.load.update(queued=6, free_slots=0, p50_queue_wait_s=7.2)
        lb = ServingLoadBalancer([b0.addr, b1.addr], retry_after_s=1.0)
        lb.health_check()
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": [1]})
            assert ei.value.code == 503
            assert "saturated" in json.load(ei.value)["error"]
            assert int(ei.value.headers["Retry-After"]) >= 8  # ceil(7.2)
            assert lb.shed_total == 1
            # shed is visible on the LB's own /healthz
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz"))
            assert body["shed_total"] == 1
            # neither stub saw the shed request
            assert b0.requests == 0 and b1.requests == 0
        finally:
            srv.stop()

    def test_one_unsaturated_backend_absorbs(self, load_backends):
        b0, b1 = load_backends
        b0.load.update(queued=6, free_slots=0)     # saturated
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        lb.health_check()
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            out = json.load(_post(
                f"http://127.0.0.1:{srv.port}/v1/generate",
                {"tokens": [1]}))
            assert out["backend"] == "b1"
            assert lb.shed_total == 0
        finally:
            srv.stop()

    def test_no_load_report_never_saturates(self, backends):
        """Pre-ISSUE-7 backends (plain {"ok": true} health) have no
        watermark: the LB must keep dispatching, not shed."""
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        lb.health_check()
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            out = json.load(_post(
                f"http://127.0.0.1:{srv.port}/v1/generate",
                {"tokens": [1]}))
            assert out["backend"] in ("b0", "b1")
            assert lb.shed_total == 0
        finally:
            srv.stop()

    def test_queue_watermark_override(self, load_backends):
        """An explicit LB-level watermark sheds even when the engines'
        own max_queue would not."""
        b0, b1 = load_backends
        for b in (b0, b1):
            b.load.update(queued=2, free_slots=0, max_queue=0)
        lb = ServingLoadBalancer([b0.addr, b1.addr], queue_watermark=2)
        lb.health_check()
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": [1]})
            assert ei.value.code == 503
            assert lb.shed_total == 1
        finally:
            srv.stop()

    def test_relayed_engine_429_keeps_retry_after(self, load_backends):
        """An engine-level admission shed (HTTP 429 from the backend)
        relays through the LB with its Retry-After intact."""
        b0, b1 = load_backends

        def overloaded(q):
            raise RestError(429, "engine queue full",
                            headers={"Retry-After": "5"})
        # rebuild b0's router with an overloaded generate
        b0._srv.stop()
        r = Router()
        r.post("/v1/generate", overloaded)
        r.get("/healthz", b0._healthz)
        b0._srv = JsonHttpServer(r, port=0).start()
        b0.addr = f"127.0.0.1:{b0._srv.port}"
        lb = ServingLoadBalancer([b0.addr])
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": [1]})
            assert ei.value.code == 429
            assert ei.value.headers["Retry-After"] == "5"
            # a 429 is the backend SPEAKING http: stays healthy, streak 0
            snap = lb.backends()[0]
            assert snap["healthy"] and snap["consecutive_failures"] == 0
        finally:
            srv.stop()


class TestCacheAffinity:
    """ISSUE 12: cache-affine dispatch — session/prefix keys re-land on
    the backend holding their KV blocks, WITHOUT ever overriding health,
    draining, or saturation."""

    def _front(self, lb):
        return JsonHttpServer(lb.router(), port=0).start()

    def test_session_sticks_to_one_backend(self, load_backends):
        b0, b1 = load_backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        lb.health_check()
        srv = self._front(lb)
        try:
            served = set()
            for _ in range(4):
                out = json.load(_post(
                    f"http://127.0.0.1:{srv.port}/v1/generate",
                    {"tokens": [1], "session": "conv-7"}))
                served.add(out["backend"])
            assert len(served) == 1         # pinned by the affinity map
            assert lb.affinity_hits >= 3    # first is "new", rest hit
            assert lb.affinity_new >= 1
            assert lb.metrics_affinity.value(outcome="hit") >= 3
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz"))
            assert body["affinity_hits"] == lb.affinity_hits
        finally:
            srv.stop()

    def test_resident_prefix_hint_steers_first_dispatch(
            self, load_backends):
        """A key never seen by the LB but reported resident by a
        backend's load report routes there — the engine-side hint path."""
        b0, b1 = load_backends
        b1.load["resident_prefixes"] = ["s:warm-sess"]
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        lb.health_check()
        srv = self._front(lb)
        try:
            out = json.load(_post(
                f"http://127.0.0.1:{srv.port}/v1/generate",
                {"tokens": [1], "session": "warm-sess"}))
            assert out["backend"] == "b1"
            assert lb.affinity_hits == 1
        finally:
            srv.stop()

    def test_affinity_never_overrides_saturation(self, load_backends):
        """The pinned backend saturates -> the session REROUTES to the
        other backend instead of queueing onto its cache."""
        b0, b1 = load_backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        lb.health_check()
        srv = self._front(lb)
        try:
            out = json.load(_post(
                f"http://127.0.0.1:{srv.port}/v1/generate",
                {"tokens": [1], "session": "conv-1"}))
            pinned = out["backend"]
            sat = b0 if pinned == "b0" else b1
            other = "b1" if pinned == "b0" else "b0"
            sat.load.update(queued=6, free_slots=0)     # past watermark
            lb.health_check()
            out = json.load(_post(
                f"http://127.0.0.1:{srv.port}/v1/generate",
                {"tokens": [1], "session": "conv-1"}))
            assert out["backend"] == other
            assert lb.affinity_rerouted >= 1
        finally:
            srv.stop()

    def test_affinity_yields_to_drain_and_stale_pin_cannot_resurrect(
            self, load_backends):
        """The ISSUE-12 leg of the _release/set_backends drain race: a
        session pinned to a backend that then drains must re-route (the
        map entry is stale, not authoritative), and a stale release of
        the drained Backend must not delete the re-added address the
        affinity map now points at again."""
        b0, b1 = load_backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        lb.health_check()
        srv = self._front(lb)
        try:
            out = json.load(_post(
                f"http://127.0.0.1:{srv.port}/v1/generate",
                {"tokens": [1], "session": "conv-d"}))
            pinned_name = out["backend"]
            pinned = b0 if pinned_name == "b0" else b1
            survivor = b1 if pinned is b0 else b0
            old = lb._backends[pinned.addr]
            old.in_flight = 1                  # a request still in flight
            lb.set_backends([survivor.addr])   # scale-down: pinned drains
            out = json.load(_post(
                f"http://127.0.0.1:{srv.port}/v1/generate",
                {"tokens": [1], "session": "conv-d"}))
            assert out["backend"] == survivor.name   # re-routed, pinned
            lb._release(old)                   # drain completes: popped
            assert pinned.addr not in lb._backends
            lb.set_backends([pinned.addr, survivor.addr])
            fresh = lb._backends[pinned.addr]
            assert fresh is not old
            lb.health_check()
            out = json.load(_post(
                f"http://127.0.0.1:{srv.port}/v1/generate",
                {"tokens": [1], "session": "conv-d"}))
            assert out["backend"] in (pinned.name, survivor.name)
            # the STALE release fires after the re-add: identity check
            # keeps the fresh Backend (and its affinity pins) alive
            lb._release(old)
            assert lb._backends.get(pinned.addr) is fresh
        finally:
            srv.stop()

    def test_affinity_disabled_ignores_keys(self, load_backends):
        b0, b1 = load_backends
        b0.load["queued"] = 3
        lb = ServingLoadBalancer([b0.addr, b1.addr], affinity=False)
        lb.health_check()
        srv = self._front(lb)
        try:
            for _ in range(3):
                out = json.load(_post(
                    f"http://127.0.0.1:{srv.port}/v1/generate",
                    {"tokens": [1], "session": "conv-x"}))
                assert out["backend"] == "b1"   # pure load scoring
            assert lb.affinity_hits == 0 and lb.affinity_new == 0
        finally:
            srv.stop()

    def test_block_occupancy_breaks_score_ties(self, load_backends):
        """Equal queues, different paged-KV occupancy: the emptier pool
        wins the tie (strictly sub-request weight — it can never beat a
        real queue-depth difference)."""
        b0, b1 = load_backends
        b0.load.update(kv_blocks_live=30, kv_blocks_total=32)
        b1.load.update(kv_blocks_live=2, kv_blocks_total=32)
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        lb.health_check()
        srv = self._front(lb)
        try:
            out = json.load(_post(
                f"http://127.0.0.1:{srv.port}/v1/generate",
                {"tokens": [1]}))
            assert out["backend"] == "b1"
        finally:
            srv.stop()


class TestSlotFreeRateRetryAfter:
    def test_shed_retry_after_uses_reported_slot_free_rate(
            self, load_backends):
        """ISSUE 12 satellite: saturated-fleet 503s price Retry-After
        from the continuous-batching slot-free rate (queued / rate),
        taking the MINIMUM across backends — the soonest any backend
        frees capacity — instead of the step-boundary p50 estimate that
        overestimated the wait."""
        b0, b1 = load_backends
        for b in (b0, b1):
            b.load.update(queued=6, free_slots=0, p50_queue_wait_s=30.0)
        b0.load["slot_free_rate"] = 2.0      # 6 queued / 2 per s = 3 s
        b1.load["slot_free_rate"] = 0.5      # would be 12 s
        lb = ServingLoadBalancer([b0.addr, b1.addr], retry_after_s=1.0)
        lb.health_check()
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{srv.port}/v1/generate",
                      {"tokens": [1]})
            assert ei.value.code == 503
            # min(3 s, 12 s) = 3 s, NOT the 30 s p50 fallback
            assert int(ei.value.headers["Retry-After"]) == 3
        finally:
            srv.stop()


class TestCircuitBreaker:
    def test_breaker_opens_after_consecutive_failures(self, backends):
        """failure_threshold transport failures open the circuit: the
        backend is held out of dispatch for the cooldown even though its
        /healthz probe succeeds, then rejoins after it."""
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr],
                                 failure_threshold=2,
                                 breaker_cooldown_s=0.3)
        back = {b.addr: b for b in lb._backends.values()}
        victim = back[b0.addr]
        lb._mark_unhealthy(victim, "boom-1")
        assert not lb.backends()[0]["circuit_open"] or lb.breaker_trips == 0
        lb._mark_unhealthy(victim, "boom-2")
        assert lb.breaker_trips == 1
        # probe succeeds (stub is fine) -> healthy again, but the open
        # circuit still holds it out of dispatch
        assert lb.health_check() == 2
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            for _ in range(4):
                out = json.load(_post(
                    f"http://127.0.0.1:{srv.port}/v1/generate",
                    {"tokens": [1]}))
                assert out["backend"] == b1.name
            time.sleep(0.35)                    # cooldown passes
            served = set()
            for _ in range(8):
                out = json.load(_post(
                    f"http://127.0.0.1:{srv.port}/v1/generate",
                    {"tokens": [1]}))
                served.add(out["backend"])
            assert b0.name in served            # rejoined dispatch
        finally:
            srv.stop()

    def test_success_resets_failure_streak(self, backends):
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr], failure_threshold=3)
        victim = next(iter(lb._backends.values()))
        lb._mark_unhealthy(victim, "boom")
        lb._mark_unhealthy(victim, "boom")
        lb.health_check()
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            out = json.load(_post(
                f"http://127.0.0.1:{srv.port}/v1/generate",
                {"tokens": [1]}))
        finally:
            srv.stop()
        assert out["backend"] == b0.name
        assert lb.backends()[0]["consecutive_failures"] == 0
        # two MORE failures do not trip: the streak restarted at 0
        lb._mark_unhealthy(victim, "boom")
        lb._mark_unhealthy(victim, "boom")
        assert lb.breaker_trips == 0

    def test_healthz_not_ok_while_every_circuit_open(self, backends):
        """An all-circuits-open fleet serves nothing: the LB's own
        /healthz must go red even though every backend probe succeeds."""
        b0, _ = backends
        lb = ServingLoadBalancer([b0.addr], failure_threshold=1,
                                 breaker_cooldown_s=0.3)
        victim = next(iter(lb._backends.values()))
        lb._mark_unhealthy(victim, "boom")
        assert lb.health_check() == 1          # probe succeeds...
        srv = JsonHttpServer(lb.router(), port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/healthz")
            assert ei.value.code == 503        # ...but the front is down
            assert json.load(ei.value)["ok"] is False
            time.sleep(0.35)                   # cooldown passes
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz"))
            assert body["ok"] is True
        finally:
            srv.stop()


class TestDrainRaceRegression:
    def test_stale_release_cannot_delete_readded_backend(self, backends):
        """ISSUE 7 satellite: an address whose draining Backend completed
        its drain (popped) and was then re-added gets a NEW Backend
        object. A stale release still holding the OLD draining object
        must not delete the new owner of the address."""
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        old = lb._backends[b0.addr]
        old.in_flight = 1                      # a request in flight
        lb.set_backends([b1.addr])             # scale-down: b0 drains
        assert old.draining
        # drain completes normally: release pops the old object
        lb._release(old)
        assert b0.addr not in lb._backends
        # address re-added: a fresh Backend owns it now
        lb.set_backends([b0.addr, b1.addr])
        fresh = lb._backends[b0.addr]
        assert fresh is not old
        # the STALE release fires (old object: draining, in_flight 0):
        # pre-fix this popped b0.addr and deleted the healthy backend
        lb._release(old)
        assert lb._backends.get(b0.addr) is fresh
        # and in_flight never goes negative on double release
        assert old.in_flight == 0

    def test_release_after_drain_revert_keeps_backend(self, backends):
        """Re-added while draining WITH requests in flight: same object,
        draining reverted — the eventual release must keep it."""
        b0, b1 = backends
        lb = ServingLoadBalancer([b0.addr, b1.addr])
        b = lb._backends[b0.addr]
        b.in_flight = 1
        lb.set_backends([b1.addr])             # drains b0
        lb.set_backends([b0.addr, b1.addr])    # reverted before release
        assert not b.draining
        lb._release(b)
        assert b0.addr in lb._backends
        assert lb._backends[b0.addr] is b
