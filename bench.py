"""Benchmarks for the BASELINE.md configs. Default (no subcommand) is the
flagship Llama training-throughput bench the driver runs every round.

  python bench.py              # config 2: Llama train tokens/s/chip (+MFU)
  python bench.py serving      # config 5: tokens/s/chip, p50/p99 TTFT+latency
  python bench.py resnet       # config 1: ResNet-50 images/s/chip
  python bench.py mixtral      # config 3: MoE train tokens/s/chip
  python bench.py hpo          # config 4: in-process sweep trials/hour
  python bench.py controlplane # reconciles/s + copy-counter O(matches) proof
  python bench.py schedule     # gang-scheduler storm: FIFO vs priority

Each invocation prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline", ...extras}.

The reference publishes no numbers (BASELINE.md: `"published": {}`); the
baselines below are this framework's own first measurements, so
vs_baseline tracks our progress — BASELINE.md's "to be established, not
matched" contract.
"""

from __future__ import annotations

import argparse
import itertools
import json
import time

# Round-1/3 reference points on a single TPU v5e chip. Updated when a bench
# config changes; 0.0 means "first measurement pending" (vs_baseline: 1.0).
BASELINES = {
    "train": 14500.0,      # tokens/s/chip, Llama ~700M bs8 x seq2048 (r1)
    "serving": 0.0,        # tokens/s/chip generated
    "serving8b": 0.0,      # tokens/s/chip generated, llama3-8b int8
    "resnet": 0.0,         # images/s/chip
    "vit": 0.0,            # images/s/chip, ViT-B/16
    "mixtral": 0.0,        # tokens/s/chip
    "serving_mixtral": 0.0,  # tokens/s/chip generated, MoE family
    "hpo": 0.0,            # trials/hour (shared-compile in-process sweep)
    "hpo_platform": 0.0,   # trials/hour through StudyJob->TpuJob->gang
    "controlplane": 0.0,   # reconciles/s, N-job sweep to convergence
}

# Config-3 arch (350M-active MoE, one v5e chip): shared by the mixtral
# train bench and the MoE serving bench so "same arch" cannot drift.
MIXTRAL_ARCH = dict(
    vocab_size=32000, embed_dim=1024, num_layers=6, num_heads=16,
    num_kv_heads=8, head_dim=64, mlp_dim=2048, num_experts=8,
)

# Falsification probe for the "config-3's 25-26% MFU ceiling is the small
# arch, not the framework" claim (BASELINE.md round-4b): same family with
# head_dim 128 (the dense model's well-tiling size) and 2x wider expert
# matmuls ([*, 2048]x[2048, 2048]), still one-chip-sized (~835M total,
# ~380M active). If the claim is right this config should clear ~40% MFU
# on the SAME framework code; if it doesn't, the framework has a real MoE
# bottleneck to find. `bench.py mixtral --arch d128`.
MIXTRAL_D128_ARCH = dict(
    vocab_size=32000, embed_dim=2048, num_layers=6, num_heads=16,
    num_kv_heads=8, head_dim=128, mlp_dim=2048, num_experts=8,
)


def _emit(metric: str, value: float, unit: str, baseline: float, **extra):
    print(json.dumps({
        "metric": metric,
        "value": round(value, 1),
        "unit": unit,
        "vs_baseline": round(value / baseline, 3) if baseline > 0 else 1.0,
        **extra,
    }))


def _sync(metrics):
    """Host fetch, not block_until_ready: remote-relay TPU platforms treat
    block_until_ready as a no-op, so only a device->host transfer is a
    reliable synchronisation point."""
    import jax

    return float(jax.tree.leaves(metrics)[0])


# ---------------------------------------------------------------- config 2


def bench_train(args) -> None:
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import Llama, LlamaConfig
    from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh
    from kubeflow_tpu.train import TrainConfig, Trainer
    from kubeflow_tpu.train.data import SyntheticTextConfig, synthetic_text
    from kubeflow_tpu.train.flops import (
        device_peak_tflops,
        train_flops_per_token,
    )

    # ~700M-param Llama: big enough that the MXU dominates, small enough
    # for one v5e chip (16G HBM) with f32 Adam state + grads + activations.
    import jax.numpy as _jnp

    # bs 12 saturates one v5e chip best (measured: 8 -> 49.5% MFU,
    # 12 -> 53.4%, 16 spills). With the qkv_attn remat policy + bf16 mu +
    # bf16 logits (the round-3 defaults below), bs12 measures 55.9% MFU
    # vs 53.4% for full remat at the same batch.
    bs = args.batch_size or 12
    policy = args.remat_policy or "qkv_attn"
    cfg = LlamaConfig(
        vocab_size=32000, embed_dim=2048, num_layers=12, num_heads=16,
        num_kv_heads=8, head_dim=128, mlp_dim=5632,
        max_seq_len=args.seq_len, scan_layers=True,
        remat=policy != "none",
        remat_policy=policy if policy != "none" else "full",
        logits_f32=not args.bf16_logits,
        param_dtype=_jnp.dtype(args.param_dtype),
    )
    model = Llama(cfg)
    ndev = len(jax.devices())
    mesh = make_host_local_mesh(AxisSpec(dp=-1))
    trainer = Trainer(
        model,
        TrainConfig(task="lm", warmup_steps=10, total_steps=1000,
                    attn_impl=args.attn, mu_dtype=args.mu_dtype,
                    loss_chunk=args.loss_chunk or 0,
                    grad_accum_steps=args.grad_accum),
        mesh,
    )
    loader = None
    if args.loader == "native":
        # C++ ring-buffer pipeline: every step consumes a FRESH batch (the
        # synthetic path reuses one device batch, which cannot prove the
        # input pipeline sustains the step rate — VERDICT r3 Weak #3).
        from kubeflow_tpu.train.native_loader import NativeTokenLoader

        # seq_len + 1: the LM step shifts inputs/labels, so rows carry one
        # extra token to train at the full seq_len (synthetic_text's and
        # train.runner's contract).
        it = loader = NativeTokenLoader(
            batch_size=bs * ndev, seq_len=args.seq_len + 1,
            vocab_size=cfg.vocab_size, token_file=args.data_path,
        )
    else:
        it = synthetic_text(
            SyntheticTextConfig(
                batch_size=bs * ndev,
                seq_len=args.seq_len,
                vocab_size=cfg.vocab_size,
            )
        )

    def fresh_batch():
        return trainer.shard_batch(
            {k: jnp.asarray(v) for k, v in next(it).items()})

    batch = fresh_batch()
    state = trainer.init_state(jax.random.PRNGKey(0), batch)

    for _ in range(args.warmup):
        state, metrics = trainer.step(
            state, fresh_batch() if loader else batch)
    if args.warmup > 0:
        _sync(metrics["loss"])

    if loader is not None:
        stalls_before = loader.stalls
    if args.trace_dir:
        jax.profiler.start_trace(args.trace_dir)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = trainer.step(
            state, fresh_batch() if loader else batch)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    if args.trace_dir:
        jax.profiler.stop_trace()
    assert final_loss == final_loss, "loss is NaN"

    tokens = bs * ndev * args.seq_len * args.steps
    tps_chip = tokens / dt / ndev
    flops_per_token = train_flops_per_token(cfg, args.seq_len)
    peak = device_peak_tflops()
    mfu = tps_chip * flops_per_token / (peak * 1e12) if peak > 0 else 0.0
    extra = {}
    if loader is not None:
        extra = {"loader": "native",
                 "loader_stalls": loader.stalls - stalls_before,
                 "corpus": args.data_path or "synthetic-native"}
    _emit(
        "llama_700m_train_tokens_per_sec_per_chip", tps_chip, "tokens/s/chip",
        BASELINES["train"],
        mfu=round(mfu, 4),
        model_tflops_per_chip=round(tps_chip * flops_per_token / 1e12, 2),
        attn=args.attn,
        **extra,
    )

    if args.profile:
        # Profiled leg, SAME compiled fn and state. Session throughput
        # fluctuates at a seconds timescale far more than the profiler
        # costs (BASELINE.md: A/B in ONE process, min-of-3), so a single
        # sequential A/B measures the noise, not the overhead: run many
        # short alternating control/profiled windows — ABBA order, so a
        # slow OS/XLA state or a drift trend hits both legs equally —
        # and compare best window against best window (the noise-floor
        # estimator). The control runs a DISABLED profiler, i.e. the
        # exact hot-loop cost production pays with profiling off, and
        # the gate is one-sided: profiled merely *faster* is noise.
        from kubeflow_tpu.obs.profiler import Profiler

        prof = Profiler()
        null_prof = Profiler(enabled=False)
        pairs = 6
        leg_steps = max(1, args.steps // 2)
        leg_tokens = bs * ndev * args.seq_len * leg_steps
        step_no = itertools.count(1)  # unique across windows

        def _leg(profiler, state):
            t0 = time.perf_counter()
            for _ in range(leg_steps):
                h = profiler.start_step("train", next(step_no))
                if loader:
                    raw = next(it)
                    h.mark("data_load")
                    b = trainer.shard_batch(
                        {k: jnp.asarray(v) for k, v in raw.items()})
                    h.mark("host_to_device")
                else:
                    b = batch
                    h.mark("data_load")
                    h.mark("host_to_device")
                state, metrics = trainer.step(state, b)
                h.mark("step_compute")
                profiler.finish_step(h)
            float(metrics["loss"])
            return state, leg_tokens / (time.perf_counter() - t0) / ndev

        ctl, prf = [], []
        for r in range(pairs):
            order = [(null_prof, ctl), (prof, prf)]
            if r % 2:
                order.reverse()
            for profiler, series in order:
                state, t = _leg(profiler, state)
                series.append(t)
        prof_tps = max(prf)
        prof_mfu = prof.set_train_mfu(tokens_per_sec=prof_tps,
                                      flops_per_token=flops_per_token)
        overhead = max(0.0, 1.0 - prof_tps / max(ctl))
        if overhead > 0.02:
            raise SystemExit(
                f"train --profile: profiler overhead {overhead:.1%} "
                f"exceeds the 2% budget ({prof_tps:.0f} vs "
                f"{max(ctl):.0f} tok/s/chip, best of {pairs} "
                f"interleaved windows each)")
        s = prof.summary()["train"]
        if not s["conservation_ok"] or s["steps"] != pairs * leg_steps:
            raise SystemExit(
                f"train --profile: phase/step conservation broken or "
                f"steps lost — {s['steps']}/{pairs * leg_steps} steps, "
                f"conservation_ok={s['conservation_ok']}")
        _emit(
            "llama_700m_train_profiled_tokens_per_sec_per_chip",
            prof_tps, "tokens/s/chip", 0.0,
            profile_overhead_pct=round(overhead * 100, 2),
            phase_fractions={k: round(v, 4)
                             for k, v in sorted(s["fractions"].items())},
            mfu=round(prof_mfu, 4),
        )
    if loader is not None:
        loader.close()


# ---------------------------------------------------------------- config 5


def bench_serving(args) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models import Llama, LlamaConfig, Mixtral, MixtralConfig
    from kubeflow_tpu.serving import ServingConfig, ServingEngine

    if args.model == "mixtral":
        # The MoE family through the SAME engine (it is model-generic —
        # top-2 routing rides the cache/decode path like dense Llama);
        # arch and capacity factor shared with the mixtral train bench.
        cfg = MixtralConfig(
            **MIXTRAL_ARCH,
            # Unrolled for decode: the scanned stacked KV cache pays a
            # whole-layer-cache slice+writeback per scan step.
            max_seq_len=1024, scan_layers=False, remat=False,
            capacity_factor=args.capacity_factor or 2.0,
            kv_cache_dtype=args.quantize_kv
            if args.quantize_kv is not None else "int8",
            decode_staging=args.decode_chunk,
        )
        model = Mixtral(cfg)
        metric = "mixtral_moe_serving_tokens_per_sec_per_chip"
        baseline = BASELINES["serving_mixtral"]
        # r4 final sweep (staged decode + int8 KV, the default): bs64
        # 10,646 (TTFT 0.90s) -> bs128 18,273 (TTFT 1.10s — the same SLO
        # class as the 700M default; 1.7x bs64's tokens) -> bs192 21,305
        # (1.39s) -> bs256 22,610 (1.76s).
        default_bs = 128
    else:
        cfg = LlamaConfig(
            vocab_size=32000, embed_dim=2048, num_layers=12, num_heads=16,
            num_kv_heads=8, head_dim=128, mlp_dim=5632,
            # Unrolled for decode (+18% gen tok/s vs scanned: no stacked-
            # cache slice+writeback per scan step; BASELINE.md).
            max_seq_len=1024, scan_layers=False, remat=False,
            kv_cache_dtype=args.quantize_kv
            if args.quantize_kv is not None else "int8",
            decode_staging=args.decode_chunk,
        )
        model = Llama(cfg)
        metric = "llama_700m_serving_tokens_per_sec_per_chip"
        baseline = BASELINES["serving"]
        # r4 final sweep (staged decode + int8 KV, the default): bs48
        # 6,558 (TTFT 1.08s — the round-start record served 1,948 at
        # 1.13s) -> 96 9,058 (1.56s) -> 160 9,875 (2.4s); 48 balances
        # the SLO. bf16 KV (--quantize-kv ''): bs48 5,559.
        default_bs = 48
    params = {"params": model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32)
    )["params"]}
    # Larger batches amortise the per-step param read until TTFT-hurting
    # wave effects dominate; per-model defaults above, explicit flag wins.
    bs = args.batch_size or default_bs
    requests = args.requests or 2 * bs
    engine = ServingEngine(
        model, params,
        ServingConfig(max_batch=bs, max_len=1024,
                      decode_chunk=args.decode_chunk,
                      quantize=args.quantize),
    )
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=args.prompt_len).tolist()
        for _ in range(requests)
    ]
    # Warmup: AOT-compile every prefill k-variant + the decode chunk, then
    # one real round so device buffers exist.
    engine.warmup(args.prompt_len)
    engine.submit(prompts[0], max_new_tokens=args.decode_chunk + 1)
    engine.run()

    engine.decode_dispatches = 0
    t0 = time.perf_counter()
    rids = [engine.submit(p, max_new_tokens=args.gen_len) for p in prompts]
    engine.run()
    dt = time.perf_counter() - t0
    res = [engine.result(r) for r in rids]
    gen_tokens = sum(len(r.tokens) for r in res)
    ndev = len(jax.devices())
    ttfts = sorted(r.ttft_s for r in res)
    lats = sorted(r.latency_s for r in res)

    def pct(xs, p):
        return xs[min(len(xs) - 1, int(p * len(xs)))]

    _emit(
        metric,
        gen_tokens / dt / ndev, "tokens/s/chip", baseline,
        p50_ttft_s=round(pct(ttfts, 0.50), 4),
        p99_ttft_s=round(pct(ttfts, 0.99), 4),
        p50_latency_s=round(pct(lats, 0.50), 4),
        p99_latency_s=round(pct(lats, 0.99), 4),
        # Hardware-independent cost: TTFT/latency through the axon tunnel
        # are relay-bound (~110ms/dispatch); dispatches/token transfers.
        dispatches_per_token=round(
            engine.decode_dispatches / max(1, gen_tokens), 4),
        requests=requests, batch=bs,
        prompt_len=args.prompt_len, gen_len=args.gen_len,
        decode_chunk=args.decode_chunk,
    )


def bench_serving8b(args) -> None:
    """BASELINE config 5 at FLAGSHIP scale: llama3-8b, int8 weight-only,
    one v5e chip. Weights are random-init (throughput is weight-agnostic);
    the engine's lazy init+quantize fuses into one program so the bf16
    weights never sit in HBM beside the int8 copy. Reports the
    hardware-independent dispatches/token alongside tok/s (TTFT through
    the axon tunnel is dominated by ~110ms/dispatch relay)."""
    import jax
    import numpy as np

    from kubeflow_tpu.models import get_model
    from kubeflow_tpu.serving import ServingConfig, ServingEngine

    # scan_layers=False: the per-step int8->bf16 dequant of SCANNED
    # stacked weights materialises the full 16G bf16 tree (measured OOM);
    # unrolled layers let XLA fuse the dequant per layer. Costs ~4-7 min
    # of one-time compile through the tunnel.
    # int8 KV by default: with the staged flush it strictly wins at 8B
    # (bs48 1,945 tok/s at BETTER TTFT than bf16 bs40's 1,631; ladder to
    # 2,804 @ bs96). --quantize-kv '' selects the bf16 cache.
    kv = args.quantize_kv if args.quantize_kv is not None else "int8"
    bs = args.batch_size or 48
    # --paged (ISSUE 18): the decode cache is the physically paged pool.
    # Dense HBM is bs x max_len rows per layer whether used or not (the
    # bs112 OOM wall of r04); the pool is kv_blocks x kv_block_size rows
    # TOTAL, sized to actual demand — ceil((prompt+gen)/block) blocks per
    # concurrent sequence plus one fork-slack block each — so bs112 and
    # 32k max_len fit the same 16G chip.
    paged = getattr(args, "paged", False)
    pbs = args.kv_block_size
    blocks_per_seq = -(-(args.prompt_len + args.gen_len) // pbs)
    kv_blocks = args.kv_blocks or bs * (blocks_per_seq + 1)
    paged_model_kw = (
        {"paged_kv_blocks": kv_blocks, "paged_kv_block_size": pbs}
        if paged else {})
    model, mcfg = get_model(
        "llama3-8b", param_dtype="bfloat16",
        max_seq_len=args.max_len, scan_layers=False, remat=False,
        kv_cache_dtype=kv,
        decode_staging=args.decode_chunk,
        **paged_model_kw,
    )

    def params():
        import jax.numpy as jnp

        return {"params": model.init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32),
            decode=True,
        )["params"]}

    # Measured ladder (r4, one v5e chip, staged decode + int8 KV):
    # bs48 1,945 (TTFT 3.8s, BELOW the round-start record's 4.4s SLO) ->
    # 64 2,152 -> 80 2,509 -> 96 2,804 -> 112 OOM. bf16-KV tops at bs40
    # 1,631. int8 KV is also what makes max_len 1024 x 512-token prompts
    # possible at all: 898 tok/s at bs24.
    requests = args.requests or 2 * bs
    bucket = 1 << (args.prompt_len - 1).bit_length()
    paged_serve_kw = (
        {"kv_blocks": kv_blocks, "kv_block_size": pbs} if paged else {})
    engine = ServingEngine(
        model, params,
        ServingConfig(
            max_batch=bs, max_len=args.max_len,
            decode_chunk=args.decode_chunk,
            quantize=args.quantize or "int8",
            param_dtype="bfloat16",
            prefill_buckets=(bucket,),
            **paged_serve_kw,
        ),
    )
    kv_note = {"quantize_kv": kv} if kv else {}
    rng = np.random.default_rng(0)
    # --shared-prefix-len: the prefix-heavy COW leg — every prompt opens
    # with the same head (system-prompt shape), so in paged mode the
    # sharers' leading blocks map to the SAME physical pages and the
    # pool holds more concurrent sequences than its no-sharing capacity.
    shared = min(args.shared_prefix_len, args.prompt_len)
    head = rng.integers(1, mcfg.vocab_size, size=shared).tolist()
    prompts = [
        head + rng.integers(
            1, mcfg.vocab_size, size=args.prompt_len - shared).tolist()
        for _ in range(requests)
    ]
    engine.warmup(args.prompt_len)
    engine.submit(prompts[0], max_new_tokens=args.decode_chunk + 1)
    engine.run()

    engine.decode_dispatches = 0
    t0 = time.perf_counter()
    rids = [engine.submit(p, max_new_tokens=args.gen_len) for p in prompts]
    engine.run()
    dt = time.perf_counter() - t0
    res = [engine.result(r) for r in rids]
    gen_tokens = sum(len(r.tokens) for r in res)
    ndev = len(jax.devices())
    ttfts = sorted(r.ttft_s for r in res)
    lats = sorted(r.latency_s for r in res)

    def pct(xs, p):
        return xs[min(len(xs) - 1, int(p * len(xs)))]

    paged_note = {}
    if paged:
        # Hard gates the bench leg rides on: the two-layer COW
        # conservation invariant must hold after the drain, and a
        # prefix-heavy leg must actually have shared pages (non-vacuous).
        engine.blocks.check_conservation()
        snap = engine.blocks.snapshot()
        if shared >= pbs:
            assert snap["kv_shared_refs_total"] > 0, (
                "prefix-heavy paged leg shared zero blocks")
        paged_note = {
            "paged": True, "kv_blocks": kv_blocks, "kv_block_size": pbs,
            "kv_pool_rows": (kv_blocks + 1) * pbs,
            "dense_cache_rows": bs * args.max_len,
            "cow_copies_total": snap["kv_cow_copies_total"],
            "shared_refs_total": snap["kv_shared_refs_total"],
        }
        if shared:
            paged_note["shared_prefix_len"] = shared
    _emit(
        "llama3_8b_serving_tokens_per_sec_per_chip",
        gen_tokens / dt / ndev, "tokens/s/chip",
        BASELINES.get("serving8b", 0.0),
        p50_ttft_s=round(pct(ttfts, 0.50), 4),
        p99_ttft_s=round(pct(ttfts, 0.99), 4),
        p50_latency_s=round(pct(lats, 0.50), 4),
        dispatches_per_token=round(
            engine.decode_dispatches / max(1, gen_tokens), 4),
        quantize=args.quantize or "int8",
        requests=requests, batch=bs,
        prompt_len=args.prompt_len, gen_len=args.gen_len,
        decode_chunk=args.decode_chunk, max_len=args.max_len,
        **kv_note,
        **paged_note,
    )

    if args.profile:
        # Profiled leg on the SAME engine (same compiled fns, same pool):
        # re-play the workload alternating unprofiled control and
        # profiled passes, best-of-3 each (BASELINE.md: session
        # throughput drifts ±5-25%, so A/B in ONE process, min-of-3 —
        # a single sequential pair measures the drift, not the
        # overhead). One-sided gate: only profiled *slower* than the
        # best control counts. Hard gates: <= 2% throughput overhead,
        # phase/step conservation, and the structural track floor the
        # ISSUE prescribes (>= 4 phase tracks, >= 2 counter tracks).
        from kubeflow_tpu.obs.profiler import (
            Profiler,
            perfetto_track_counts,
            serving_cost_catalog,
        )

        prof = Profiler()
        if paged:
            prof.set_catalog(serving_cost_catalog(
                mcfg, context_len=args.prompt_len, kv_block_size=pbs,
                blocks_per_seq=blocks_per_seq, batch=bs))

        def _leg(profiler):
            engine.attach_profiler(profiler)
            t0 = time.perf_counter()
            rids = [engine.submit(p, max_new_tokens=args.gen_len)
                    for p in prompts]
            engine.run()
            leg_dt = time.perf_counter() - t0
            engine.attach_profiler(None)
            toks = sum(len(engine.result(r).tokens) for r in rids)
            return toks / leg_dt / ndev

        pairs = 3
        ctl = [gen_tokens / dt / ndev]  # the main bench window counts too
        prf = []
        for r in range(pairs):
            # ABBA order: a slow scheduler state or drift trend hits
            # both legs equally instead of always taxing the second.
            if r % 2:
                prf.append(_leg(prof))
                ctl.append(_leg(None))
            else:
                ctl.append(_leg(None))
                prf.append(_leg(prof))
        prof_tps = max(prf)
        overhead = max(0.0, 1.0 - prof_tps / max(ctl))
        if overhead > 0.02:
            raise SystemExit(
                f"serving8b --profile: profiler overhead {overhead:.1%} "
                f"exceeds the 2% budget ({prof_tps:.0f} vs "
                f"{max(ctl):.0f} tok/s/chip, best of {pairs} "
                f"interleaved windows each)")
        s = prof.summary().get("serve")
        if s is None or s["steps"] == 0 or not s["conservation_ok"]:
            raise SystemExit(
                f"serving8b --profile: no profiled steps or phase/step "
                f"conservation broken — {s}")
        counts = perfetto_track_counts(prof.export_perfetto())
        if counts["phase_tracks"] < 4 or counts["counter_tracks"] < 2:
            raise SystemExit(
                f"serving8b --profile: export too thin — {counts} "
                "(need >= 4 phase tracks and >= 2 counter tracks)")
        _emit(
            "llama3_8b_serving_profiled_tokens_per_sec_per_chip",
            prof_tps, "tokens/s/chip", 0.0,
            profile_overhead_pct=round(overhead * 100, 2),
            profiled_steps=s["steps"],
            phase_fractions={k: round(v, 4)
                             for k, v in sorted(s["fractions"].items())},
            **{f"perfetto_{k}": v for k, v in sorted(counts.items())},
        )


# ---------------------------------------------------------------- config 1


def _bench_image(args, model_name: str, default_bs: int,
                 metric: str, baseline_key: str) -> None:
    """Shared image-training bench body (ResNet + ViT): one timing/warmup/
    emit sequence so the two benches cannot drift apart."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import get_model
    from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh
    from kubeflow_tpu.train import TrainConfig, Trainer
    from kubeflow_tpu.train.data import SyntheticImageConfig, synthetic_images

    model, _ = get_model(model_name)
    ndev = len(jax.devices())
    mesh = make_host_local_mesh(AxisSpec(dp=-1))
    trainer = Trainer(
        model, TrainConfig(task="image", warmup_steps=10, total_steps=1000),
        mesh,
    )
    bs = (args.batch_size or default_bs) * ndev
    it = synthetic_images(SyntheticImageConfig(batch_size=bs, image_size=224))
    batch = trainer.shard_batch(
        {k: jnp.asarray(v) for k, v in next(it).items()})
    state = trainer.init_state(jax.random.PRNGKey(0), batch)
    for _ in range(args.warmup):
        state, metrics = trainer.step(state, batch)
    if args.warmup > 0:
        _sync(metrics["loss"])
    if args.trace_dir:
        jax.profiler.start_trace(args.trace_dir)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = trainer.step(state, batch)
    _sync(metrics["loss"])
    dt = time.perf_counter() - t0
    if args.trace_dir:
        jax.profiler.stop_trace()

    # MFU via XLA's own cost model (FMA = 2 flops, the same convention as
    # device_peak_tflops) — vision archs have no single "params x tokens"
    # formula like the LLM rows, and the compiled step's counted flops is
    # the honest, convention-consistent numerator.
    from kubeflow_tpu.train.flops import device_peak_tflops
    peak = device_peak_tflops()
    mfu = {}
    if peak > 0:
        try:
            cost = trainer.step_cost_analysis(state, batch)
            # cost_analysis reports the SPMD-partitioned per-device
            # executable's flops — already per-chip, no ndev division.
            step_flops = float(cost.get("flops", 0.0))
            if step_flops > 0:
                mfu = {"mfu": round(
                    step_flops * args.steps / dt / (peak * 1e12), 4)}
        except Exception as e:  # cost analysis is best-effort per backend
            mfu = {"mfu_error": str(e)[:80]}
    _emit(
        metric, bs * args.steps / dt / ndev, "images/s/chip",
        BASELINES.get(baseline_key, 0.0),
        batch=bs, **mfu,
    )


def bench_resnet(args) -> None:
    # Conv stacks want large batches (measured: bs32 1420 -> bs128 ~2200
    # -> bs256 ~2385 -> bs512 regresses, one v5e).
    _bench_image(args, "resnet50", 256,
                 "resnet50_train_images_per_sec_per_chip", "resnet")


def bench_vit(args) -> None:
    # ViT-B/16: completes measured coverage of the model zoo. Measured r4
    # sweep on one v5e: bs32 663 -> bs48 668 -> bs64 718 -> bs128 675 ->
    # bs256 594 img/s.
    _bench_image(args, "vit-b16", 64,
                 "vit_b16_train_images_per_sec_per_chip", "vit")


# ---------------------------------------------------------------- config 3


def bench_mixtral(args) -> None:
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import Mixtral, MixtralConfig
    from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh
    from kubeflow_tpu.train import TrainConfig, Trainer
    from kubeflow_tpu.train.data import SyntheticTextConfig, synthetic_text
    from kubeflow_tpu.train.flops import (
        device_peak_tflops,
        train_flops_per_token,
    )

    # MoE sized for one v5e chip: 8 experts, ~350M params, top-2 routing.
    # capacity 1.0 (vs 1.25): -20% expert-buffer padding; with the aux
    # balance loss at 0.02 the router spreads load, so drops stay small —
    # the standard Switch/GShard production setting. Measured r4 ladder:
    # einsum 55.8k -> index-gather dispatch 63.4k -> cap 1.0 70.9k tok/s.
    policy = args.remat_policy or "minimal"
    arch = MIXTRAL_D128_ARCH if args.arch == "d128" else MIXTRAL_ARCH
    cfg = MixtralConfig(
        **arch,
        max_seq_len=args.seq_len, scan_layers=True,
        remat=policy != "none",
        remat_policy=policy if policy != "none" else "full",
        logits_f32=not args.bf16_logits,
        param_dtype=jnp.dtype(args.param_dtype),
        capacity_factor=args.capacity_factor or 1.0,
        moe_dispatch=args.moe_dispatch,
    )
    model = Mixtral(cfg)
    ndev = len(jax.devices())
    # ep shards experts when devices allow (8 virtual / multi-chip); one
    # real chip runs ep=1 with the same dispatch path.
    ep = 8 if ndev % 8 == 0 else (2 if ndev % 2 == 0 else 1)
    mesh = make_host_local_mesh(AxisSpec(dp=-1, ep=ep))
    trainer = Trainer(
        model,
        TrainConfig(task="lm", warmup_steps=10, total_steps=1000,
                    aux_loss_weight=0.02, attn_impl=args.attn,
                    mu_dtype=args.mu_dtype),
        mesh,
    )
    bs = args.batch_size or (6 if args.arch == "d128" else 8)
    it = synthetic_text(SyntheticTextConfig(
        batch_size=bs * ndev, seq_len=args.seq_len,
        vocab_size=cfg.vocab_size,
    ))
    batch = trainer.shard_batch({k: jnp.asarray(v) for k, v in next(it).items()})
    state = trainer.init_state(jax.random.PRNGKey(0), batch)
    rng = jax.random.PRNGKey(1)
    for _ in range(args.warmup):
        state, metrics = trainer.step(state, batch, rng=rng)
    if args.warmup > 0:
        _sync(metrics["loss"])
    if args.trace_dir:
        jax.profiler.start_trace(args.trace_dir)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = trainer.step(state, batch, rng=rng)
    _sync(metrics["loss"])
    dt = time.perf_counter() - t0
    if args.trace_dir:
        jax.profiler.stop_trace()
    tokens = bs * ndev * args.seq_len * args.steps
    tps_chip = tokens / dt / ndev
    flops_per_token = train_flops_per_token(cfg, args.seq_len)
    peak = device_peak_tflops()
    _emit(
        "mixtral_moe_train_tokens_per_sec_per_chip", tps_chip,
        "tokens/s/chip", BASELINES["mixtral"],
        ep=ep, arch=args.arch,
        mfu=round(tps_chip * flops_per_token / (peak * 1e12), 4)
        if peak > 0 else 0.0,
    )


# ---------------------------------------------------------------- config 4


def bench_hpo(args) -> None:
    import jax.numpy as jnp

    from kubeflow_tpu.hpo.space import ParameterSpec
    from kubeflow_tpu.hpo.sweep import SharedCompileSweep, run_study
    from kubeflow_tpu.models import get_model
    from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh
    from kubeflow_tpu.train.data import SyntheticImageConfig, synthetic_images

    model, mcfg = get_model("vit-tiny")
    mesh = make_host_local_mesh(AxisSpec(dp=-1))
    it = synthetic_images(SyntheticImageConfig(
        batch_size=args.batch_size or 8, image_size=mcfg.image_size,
        num_classes=mcfg.num_classes,
    ))
    batch = {k: jnp.asarray(v) for k, v in next(it).items()}
    # Shared-compile trials: hyperparams are traced optimizer-state inputs,
    # so only the first trial pays XLA compile.
    sweep = SharedCompileSweep(model, mesh, batch, steps=args.steps,
                               task="image")
    res = run_study(
        [
            ParameterSpec(name="learning_rate", min=1e-4, max=1e-2,
                          log_scale=True),
            ParameterSpec(name="weight_decay", min=0.0, max=0.2),
        ],
        sweep.trial_fn, algorithm="random", max_trials=args.requests or 16,
        seed=0,
    )
    _emit(
        "hpo_vit_tiny_trials_per_hour", res.trials_per_hour, "trials/hour",
        BASELINES["hpo"],
        trials=len(res.trials), steps_per_trial=args.steps,
        best_loss=round(res.best.objective, 4) if res.best else None,
    )


def bench_hpo_platform(args) -> None:
    """The PLATFORM HPO path: StudyJob CR -> one TpuJob per trial ->
    gang pods on a FakeKubelet that completes trials instantly with a
    synthetic objective. What this measures is the control plane's
    per-trial overhead (suggestion, job/pod churn, metric harvest) —
    the orders-of-magnitude-slower-but-general path next to
    SharedCompileSweep's traced-hyperparam number (which only sweeps
    params expressible as optimizer-state inputs)."""
    import json as _json
    import math

    from kubeflow_tpu.controlplane.api import ObjectMeta, TpuJobSpec
    from kubeflow_tpu.controlplane.api.types import (
        MeshAxesSpec,
        StudyJob,
        StudyJobSpec,
    )
    from kubeflow_tpu.controlplane.controllers import (
        StudyJobController,
        TpuJobController,
    )
    from kubeflow_tpu.controlplane.controllers.podrunner import FakeKubelet
    from kubeflow_tpu.controlplane.runtime import (
        ControllerManager,
        InMemoryApiServer,
    )
    from kubeflow_tpu.hpo.space import ParameterSpec
    from kubeflow_tpu.utils.monitoring import MetricsRegistry

    api = InMemoryApiServer()
    reg = MetricsRegistry()
    mgr = ControllerManager(api)
    mgr.register(TpuJobController(api, reg))
    mgr.register(StudyJobController(api, reg))

    def termination(pod):
        env = {e.name: e.value for c in pod.spec.containers for e in c.env}
        hp = _json.loads(env.get("KFTPU_HPARAMS", "{}"))
        lr = float(hp.get("learning_rate", 1.0))
        return _json.dumps(
            {"loss": (math.log10(lr) - math.log10(3e-3)) ** 2})

    kubelet = FakeKubelet(api, reg, outcome=lambda name: "Succeeded",
                          termination=termination)
    mgr.register(kubelet)

    trials = args.requests or 64
    api.create(StudyJob(
        metadata=ObjectMeta(name="bench", namespace="bench"),
        spec=StudyJobSpec(
            parameters=[
                ParameterSpec(name="learning_rate", type="double",
                              min=1e-4, max=1e-1, log_scale=True),
                ParameterSpec(name="weight_decay", type="double",
                              min=0.0, max=0.2),
            ],
            trial=TpuJobSpec(slice_type="v5e-8", model="vit-tiny",
                             mesh=MeshAxesSpec(dp=-1)),
            max_trials=trials, parallel_trials=8, seed=0,
        ),
    ))
    t0 = time.perf_counter()
    for _ in range(trials * 4):
        mgr.run_until_idle(include_timers_within=30.0)
        kubelet.tick()
        mgr.run_until_idle(include_timers_within=30.0)
        study = api.get("StudyJob", "bench", "bench")
        if study.status.condition in ("Completed", "Failed"):
            break
    dt = time.perf_counter() - t0
    assert study.status.condition == "Completed", study.status.condition
    _emit(
        "hpo_studyjob_path_trials_per_hour",
        trials / dt * 3600.0, "trials/hour",
        BASELINES.get("hpo_platform", 0.0),
        trials=trials,
        note="control-plane path: StudyJob->TpuJob->gang per trial "
             "(FakeKubelet, zero-compute trials); the SharedCompileSweep "
             "number covers traceable hyperparams only",
    )


def bench_controlplane(args) -> None:
    """Control-plane throughput (ISSUE 3's headline): N TpuJobs x 4-host
    gangs driven to Succeeded through the reconciler kernel against the
    indexed, copy-light apiserver. No JAX involved — this measures the
    coordination layer (the wall of arxiv 2011.03641), and proves the
    O(matches) list contract with a deterministic copy counter rather
    than wall-clock.

    ``--workers N`` (ISSUE 5) additionally runs the worker-pool scaling
    sweep: the SAME fleet with serial dispatch and with an N-worker pool,
    gated on final-state equality (count-based signature) between the
    two. Worker sweeps default to a modeled per-verb API RTT
    (``--rtt-us``, applied to BOTH runs): in-process at zero RTT the GIL
    serializes the pure-Python reconcile bodies and the comparison would
    measure the interpreter, not the dispatcher — real control planes
    pay ~ms apiserver round trips, which is exactly the wait
    MaxConcurrentReconciles-style pools overlap.

    ``--shards N`` (ISSUE 6) runs the HORIZONTAL scaling sweep: the same
    fleet once through the single-process baseline (workers=4, the PR-5
    configuration, same RTT) and once sharded across N shard processes —
    each with its own apiserver + manager; per-shard dispatch is serial
    at zero RTT (threads would only add GIL contention there) and keeps
    the baseline's pool size when --rtt-us sets a round trip — hard-gated
    on cross-shard union ``state_fingerprint()`` equality with the
    baseline — N stores and N GILs must converge to the byte-identical
    world one store does."""
    from kubeflow_tpu.controlplane.benchmark import run_controlplane_sweep

    jobs = args.requests or 1000

    def gates(rep, tag=""):
        # Hard gates (raise, not assert: python -O must not skip them).
        if not rep.all_succeeded:
            raise SystemExit(f"sweep{tag} did not converge: {rep.phases}")
        # The counter-based acceptance gate: a namespaced list copies
        # O(matches) objects, not O(store).
        if not rep.copies_scale_with_matches:
            raise SystemExit(
                f"list({rep.probe_namespace}){tag} copied {rep.list_copies} "
                f"objects for {rep.list_matches} matches in a "
                f"{rep.store_objects}-object store — the indexed/copy-light "
                "read path regressed to O(store)"
            )

    if args.shards > 1:
        from kubeflow_tpu.controlplane.shard import (
            host_cpu_headroom,
            run_sharded_sweep,
        )

        # Default rtt = 0: the sharded sweep exists to break the ZERO-RTT
        # GIL ceiling (PR 5's pool already covers the RTT-overlap regime,
        # and docs/controlplane-perf.md shows zero-RTT is where it stops
        # helping). An explicit --rtt-us still selects the RTT regime.
        rtt_s = (args.rtt_us or 0) * 1e-6
        # Baseline = the PR-5 workers=4 in-process configuration (an
        # explicit --workers overrides, including --workers 1 for a
        # serial baseline), same fleet, same modeled RTT.
        base_workers = args.workers if args.workers is not None else 4
        serial = run_controlplane_sweep(
            num_jobs=jobs, num_namespaces=args.namespaces,
            workers=base_workers, rtt_s=rtt_s,
        )
        gates(serial, tag=f"[workers={base_workers}]")
        # Per-shard dispatch: worker pools exist to overlap waits, so a
        # zero-RTT sharded run dispatches serially inside each shard
        # (threads only add GIL contention there); RTT runs keep the
        # baseline's pool size per shard.
        shard_workers = base_workers if rtt_s > 0 else 1
        shard_rep = run_sharded_sweep(
            num_jobs=jobs, num_namespaces=args.namespaces,
            shards=args.shards, workers=shard_workers, rtt_s=rtt_s,
        )
        if not shard_rep.all_succeeded:
            raise SystemExit(
                f"sharded sweep did not converge: {shard_rep.final_state}"
            )
        if shard_rep.state_signature != serial.state_signature:
            raise SystemExit(
                f"sharded sweep diverged: shards={args.shards} converged "
                f"to {shard_rep.final_state} but the in-process run to "
                f"{serial.final_state} — the router/colocation contract "
                "or WAL/watch resync regressed"
            )
        _emit(
            "controlplane_sharded_reconciles_per_sec",
            shard_rep.reconciles_per_sec, "reconciles/s",
            serial.reconciles_per_sec,    # baseline = in-process workers=4
            speedup_vs_workers4=round(
                shard_rep.reconciles_per_sec / serial.reconciles_per_sec, 3)
            if serial.reconciles_per_sec else 0.0,
            # The host's MEASURED multi-process CPU headroom (2-proc/1-proc
            # spin ratio): the ceiling any horizontal speedup can reach
            # here. Shared CI hosts often measure far below their core
            # count — read speedup_vs_workers4 against this, and against
            # shards× on real multicore hardware.
            host_cpu_parallel_headroom=round(host_cpu_headroom(), 3),
            serial=serial.summary(),
            final_state_identical=True,
            **shard_rep.summary(),
        )
        return

    if (args.workers or 1) <= 1:
        # An explicit --rtt-us applies to the serial run too (a silent
        # zero-RTT run would mislabel the emitted record).
        rep = run_controlplane_sweep(
            num_jobs=jobs, num_namespaces=args.namespaces,
            rtt_s=(args.rtt_us or 0) * 1e-6,
        )
        gates(rep)
        _emit(
            "controlplane_sweep_reconciles_per_sec",
            rep.reconciles_per_sec, "reconciles/s",
            BASELINES["controlplane"],
            **rep.summary(),
        )
        return

    rtt_s = (args.rtt_us if args.rtt_us is not None else 500) * 1e-6
    serial = run_controlplane_sweep(num_jobs=jobs,
                                    num_namespaces=args.namespaces,
                                    workers=1, rtt_s=rtt_s)
    gates(serial, tag="[workers=1]")
    par = run_controlplane_sweep(num_jobs=jobs,
                                 num_namespaces=args.namespaces,
                                 workers=args.workers, rtt_s=rtt_s)
    gates(par, tag=f"[workers={args.workers}]")
    if par.state_signature != serial.state_signature:
        raise SystemExit(
            f"worker-pool sweep diverged: workers={args.workers} converged "
            f"to {par.final_state} but serial to {serial.final_state} — "
            "per-key serialization or dirty-requeue semantics regressed"
        )
    _emit(
        "controlplane_workers_reconciles_per_sec",
        par.reconciles_per_sec, "reconciles/s",
        serial.reconciles_per_sec,      # baseline = the serial run
        speedup_vs_serial=round(
            par.reconciles_per_sec / serial.reconciles_per_sec, 3)
        if serial.reconciles_per_sec else 0.0,
        serial=serial.summary(),
        final_state_identical=True,
        **par.summary(),
    )


def bench_schedule(args) -> None:
    """Gang-scheduler storm (ISSUE 8): the SAME seeded mixed-priority
    arrival storm through the real control plane twice — FIFO
    (head-of-line, no preemption: the arxiv 1908.08082 baseline) vs the
    topology-aware priority scheduler (bin-packing + backfill +
    minimal-set preemption + background defrag) — on the SAME fleet.
    Logical-tick time, so every number is seed-deterministic.

    Hard gates (raise, not assert): exact gang accounting
    (placed + preempted + pending == submitted) and zero priority
    inversions in BOTH runs; both storms converge; the scheduler beats
    FIFO on fleet utilization AND on high-priority p95
    time-to-placement. The comparative gates assume the default
    CONTENDED storm (60 gangs on 8 slices): an under-loaded
    ``--requests`` (fleet rarely full) can legitimately fail them —
    preemption buys nothing when nobody queues."""
    from kubeflow_tpu.scheduler.benchmark import (
        check_storm_gates,
        run_schedule_storm,
    )

    jobs = args.requests or 60
    fleet = {
        k: int(v) for k, v in (
            kv.split("=") for kv in args.fleet.split(","))
    }
    if args.tenants:
        return bench_schedule_tenants(args, jobs, fleet)
    if args.elastic:
        return bench_schedule_elastic(args, jobs, fleet)
    common = dict(
        num_jobs=jobs, fleet_capacity=fleet, pool_size=args.pool_size,
        seed=args.seed, ckpt_every_ticks=args.ckpt_every,
    )
    fifo = run_schedule_storm(policy="fifo", **common)
    sched = run_schedule_storm(policy="priority", **common)
    for rep in (fifo, sched):
        check_storm_gates(rep)      # accounting + inversions + goodput
        if not rep.converged:
            raise SystemExit(
                f"[{rep.policy}] storm did not converge in {rep.ticks} "
                f"ticks: {rep.succeeded}+{rep.failed} terminal of "
                f"{rep.submitted}")
        if rep.queue_age_count == 0:
            raise SystemExit(
                f"[{rep.policy}] kftpu_scheduler_queue_age_seconds is "
                "empty — the contended storm must observe queue ages")
    fifo_p95 = fifo.ttp_ticks["high"]["p95"]
    sched_p95 = sched.ttp_ticks["high"]["p95"]
    if sched.utilization <= fifo.utilization:
        raise SystemExit(
            f"scheduler did not beat FIFO on fleet utilization: "
            f"{sched.utilization:.4f} <= {fifo.utilization:.4f}")
    if sched_p95 >= fifo_p95:
        raise SystemExit(
            f"scheduler did not beat FIFO on high-priority p95 "
            f"time-to-placement: {sched_p95} >= {fifo_p95} ticks")
    if args.goodput_out:
        # The utilization win re-expressed as attributed slice-seconds:
        # the priority scheduler converts queue_wait into productive
        # time on the SAME storm, conservation-gated in both runs.
        with open(args.goodput_out, "w") as f:
            json.dump({
                "bench": "schedule-goodput",
                "storm": {"jobs": jobs, "seed": args.seed,
                          "fleet": fleet, "pool_size": args.pool_size,
                          "ckpt_every_ticks": args.ckpt_every},
                "fifo": fifo.goodput,
                "priority": sched.goodput,
                "goodput_ratio_win": round(
                    sched.goodput["goodput_ratio"]
                    / max(fifo.goodput["goodput_ratio"], 1e-9), 3),
                "utilization": {"fifo": round(fifo.utilization, 4),
                                "priority": round(sched.utilization, 4)},
            }, f, indent=1, sort_keys=True)
            f.write("\n")
    _emit(
        "scheduler_fleet_utilization",
        sched.utilization, "fraction",
        fifo.utilization,              # baseline = the FIFO run
        p95_ttp_high_ticks=sched_p95,
        fifo_p95_ttp_high_ticks=fifo_p95,
        fifo=fifo.summary(),
        **sched.summary(),
    )


def bench_schedule_elastic(args, jobs: int, fleet: dict) -> None:
    """Elastic A/B (ISSUE 11): the SAME seeded mixed-priority storm under
    capacity oscillation (a seeded slice-preemption burst every 5 ticks)
    twice on one fleet — elastic gangs (shrink on preemption, grow on
    freed capacity, both zero-downtime resizes) vs restart-only — over a
    FIXED horizon so both runs attribute identical tracked slice-ticks.
    Work is width-proportional (a shrunk gang progresses at its current
    width) and every restart re-pays a cold spin-up window (the
    jax.distributed re-init an elastic resize keeps warm: VirtualFlow's
    decoupling, arxiv 2009.09523).

    Hard gates (raise, not assert):
    - goodput conservation EXACT (bit equality) in BOTH runs, zero
      priority inversions, exact gang accounting (check_storm_gates);
    - the elastic run attributes STRICTLY MORE ``productive`` and
      STRICTLY LESS ``restart_rollback + migration`` slice-ticks than
      restart-only on the same storm;
    - the elastic run actually resized (shrinks AND grows > 0) and
      consumed ZERO restart budget doing so."""
    from kubeflow_tpu.scheduler.benchmark import (
        check_storm_gates,
        run_schedule_storm,
    )

    common = dict(
        num_jobs=jobs, fleet_capacity=fleet, pool_size=args.pool_size,
        seed=args.seed, arrival_span=30, max_ticks=100,
        # Fixed cadence 2/1: the A/B's checkpoint model (a tighter
        # cadence than the FIFO bench's 3 — oscillation every 5 ticks
        # makes saves the difference between a cheap and a total roll).
        ckpt_every_ticks=2,
        chaos_at_tick=5, chaos_preempts=3, chaos_every=5,
        restart_spinup_ticks=2, width_scaled_work=True,
        stop_when_done=False,
    )
    el = run_schedule_storm(policy="priority", elastic=True, **common)
    ro = run_schedule_storm(policy="priority", elastic=False, **common)
    for rep in (el, ro):
        check_storm_gates(rep)      # accounting + inversions + goodput
    ge = el.goodput["categories_ticks"]
    gr = ro.goodput["categories_ticks"]
    el_rollback = ge["restart_rollback"] + ge["migration"]
    ro_rollback = gr["restart_rollback"] + gr["migration"]
    if el.goodput["tracked_ticks"] != ro.goodput["tracked_ticks"]:
        raise SystemExit(
            f"elastic A/B horizons diverged: {el.goodput['tracked_ticks']}"
            f" vs {ro.goodput['tracked_ticks']} tracked slice-ticks — "
            "the comparison is not apples-to-apples")
    if ge["productive"] <= gr["productive"]:
        raise SystemExit(
            f"elastic did not beat restart-only on productive "
            f"slice-ticks: {ge['productive']} <= {gr['productive']}")
    if el_rollback >= ro_rollback:
        raise SystemExit(
            f"elastic did not beat restart-only on rollback slice-ticks:"
            f" {el_rollback} >= {ro_rollback}")
    if el.shrinks == 0 or el.grows == 0:
        raise SystemExit(
            f"elastic storm is vacuous: shrinks={el.shrinks} "
            f"grows={el.grows} — no resize lifecycle exercised")
    if ro.resizes != 0:
        raise SystemExit(
            f"restart-only twin recorded {ro.resizes} resizes — the "
            "baseline is contaminated")
    out = args.elastic_out or args.goodput_out
    if out:
        with open(out, "w") as f:
            json.dump({
                "bench": "schedule-elastic",
                "storm": {"jobs": jobs, "seed": args.seed, "fleet": fleet,
                          "pool_size": args.pool_size,
                          "arrival_span": 30, "max_ticks": 100,
                          "ckpt_every_ticks": common["ckpt_every_ticks"],
                          "chaos": {"at_tick": 5, "preempts": 3,
                                    "every": 5},
                          "restart_spinup_ticks": 2,
                          "width_scaled_work": True},
                "elastic": el.summary(),
                "restart_only": ro.summary(),
                "productive_win_ticks": ge["productive"]
                - gr["productive"],
                "rollback_saved_ticks": ro_rollback - el_rollback,
                "queue_wait": {"elastic": ge["queue_wait"],
                               "restart_only": gr["queue_wait"]},
            }, f, indent=1, sort_keys=True)
            f.write("\n")
    _emit(
        "elastic_productive_slice_ticks",
        float(ge["productive"]), "slice-ticks",
        float(gr["productive"]),   # baseline = the restart-only twin
        rollback_ticks=el_rollback,
        restart_only_rollback_ticks=ro_rollback,
        restart_only=ro.summary(),
        **el.summary(),
    )


def bench_schedule_tenants(args, jobs: int, fleet: dict) -> None:
    """Multi-tenant capacity-market A/B (ISSUE 13): the SAME seeded
    multi-tenant storm — heavy-tailed per-tenant demand over the
    DEFAULT_TENANT_SPECS tree, one tenant bursting 10x in high-priority
    gangs — twice on one fleet: weighted-DRF enforcement vs the
    raw-priority observe-only baseline (the tree attached, shares
    logged, nothing enforced).

    Hard gates (count-based, raise — python -O must not skip them):
    - DRF leg: ZERO fairness violations — no gang of a tenant
      at-or-below its weighted fair share evicted by a tenant above
      fair share (check_tenant_gates; the ISSUE-13 acceptance gate),
      non-vacuous preemptions, >= 2 tenant subtrees attributed;
    - BOTH legs: exact gang accounting, zero priority inversions,
      storm convergence, goodput-ledger conservation bit-exact;
    - the baseline actually RECORDS violations (> 0) — otherwise the
      A/B proves nothing about what enforcement prevents;
    - the DRF leg's protection is non-vacuous: it refused at least one
      eviction or yielded at least one admission."""
    from kubeflow_tpu.scheduler.benchmark import (
        DEFAULT_TENANT_SPECS,
        check_storm_gates,
        check_tenant_gates,
        run_schedule_storm,
    )

    common = dict(
        num_jobs=jobs, fleet_capacity=fleet, pool_size=args.pool_size,
        seed=args.seed, ckpt_every_ticks=args.ckpt_every,
        tenants=list(DEFAULT_TENANT_SPECS),
    )
    drf = run_schedule_storm(policy="priority", drf=True, **common)
    base = run_schedule_storm(policy="priority", drf=False, **common)
    check_tenant_gates(drf)
    check_storm_gates(base)
    for rep, tag in ((drf, "drf"), (base, "priority-only")):
        if not rep.converged:
            raise SystemExit(
                f"tenants[{tag}]: storm did not converge in {rep.ticks} "
                f"ticks: {rep.succeeded}+{rep.failed} terminal of "
                f"{rep.submitted}")
    if base.fairness_violations == 0:
        raise SystemExit(
            "tenants[priority-only]: baseline recorded ZERO fairness "
            "violations — the burst never threatened anybody and the "
            "A/B is vacuous (seed/contention too low?)")
    if drf.tenant_protected == 0 and drf.tenant_yields == 0:
        raise SystemExit(
            "tenants[drf]: enforcement never engaged (zero protections "
            "AND zero admission yields) — vacuous run")
    out = args.tenants_out or args.goodput_out
    if out:
        with open(out, "w") as f:
            json.dump({
                "bench": "schedule-tenants",
                "storm": {"jobs": jobs, "submitted": drf.submitted,
                          "seed": args.seed, "fleet": fleet,
                          "pool_size": args.pool_size,
                          "ckpt_every_ticks": args.ckpt_every,
                          "tenant_specs": list(DEFAULT_TENANT_SPECS),
                          "burst_factor": 10},
                "drf": drf.summary(),
                "priority_only": base.summary(),
                "fairness_violations": {
                    "drf": drf.fairness_violations,
                    "priority_only": base.fairness_violations,
                },
                "tenants": drf.goodput.get("tenants", {}),
                "tenants_priority_only":
                    base.goodput.get("tenants", {}),
            }, f, indent=1, sort_keys=True)
            f.write("\n")
    # Headline as higher-is-better: the fraction of executed evictions
    # that respected the fairness invariant (DRF: 1.0 by construction,
    # count-gated; baseline: what raw priority actually did).
    _emit(
        "tenant_fair_preemption_fraction",
        1.0 - drf.fairness_violations / max(1, drf.preemptions),
        "fraction",
        1.0 - base.fairness_violations / max(1, base.preemptions),
        baseline_violations=base.fairness_violations,
        priority_only=base.summary(),
        **drf.summary(),
    )


def bench_serve(args) -> None:
    """Serving data-plane overload bench (ISSUE 7 + ISSUE 12): the
    open-loop generator (fixed arrival rate — requests fire on schedule
    whether or not earlier ones finished, the way real traffic does) at
    2x analytic capacity through the real ServingLoadBalancer.

    The ISSUE-7 legs (classic fixed-service double, real ServingAutoscaler
    in the third run) answer the overload question:

    1. **no-shed baseline** — the pre-ISSUE-7 plane (unbounded engine
       queue, no LB watermark): the backlog grows without bound and the
       offered excess turns into client timeouts.
    2. **shed** — bounded admission (429 + Retry-After) + LB watermark
       shedding (503 + Retry-After): admitted work keeps a bounded p99,
       goodput holds near capacity, zero timeouts.
    3. **shed + autoscale** — the ServingAutoscaler scales replicas
       toward max_replicas off the scraped queue waits: goodput climbs
       past one replica's capacity toward the offered load.

    The ISSUE-12 legs run ONE seeded variable-length session trace at 2x
    the DENSE-KV analytic capacity through three decode planes on the
    same KV budget (token-model SimServingReplica + the production
    KVBlockAllocator):

    4. **stepbatch** — the pre-ISSUE-12 plane: admission at wave
       boundaries, every sequence's KV reserved at max_len; batch
       capacity sized by the longest sequence.
    5. **continuous-dense** — mid-step admission alone (slots retire and
       refill between decode chunks), KV still reserved at worst case.
    6. **continuous-paged** — the full plane: paged block tables sized
       by actual demand, so concurrency is bounded by total KV blocks
       against real request sizes.

    Plus the cache-affinity A/B (affine vs blind routing on the same
    seeded session-replay trace; see run_affinity_bench).

    Hard gates (count-based, raise — python -O must not skip them):
    request accounting sums exactly in every leg; every shed carries
    Retry-After; the KV-block conservation invariant holds in every
    token leg (allocated == freed + live, pool exactly partitioned,
    zero blocks leaked after drain); mid-step admissions are non-zero
    in the continuous legs and exactly zero in stepbatch; the paged
    plane beats stepbatch AND the recorded SERVE_BENCH_r07 shed leg
    (0.961x goodput, 0.17 s p99) on goodput AND TTFT p99; the affinity
    run shows a hit-rate-driven TTFT separation over blind routing."""
    from kubeflow_tpu.tools.loadtest import (
        run_affinity_bench,
        run_continuous_bench,
        run_prefix_tree_bench,
        run_serve_bench,
    )

    # The recorded ISSUE-7 shed leg (SERVE_BENCH_r07.json): the numbers
    # the continuous-batching plane must beat on the same 2x-overload
    # shape — goodput vs capacity AND admitted-tail latency.
    R07_GOODPUT_VS_CAPACITY = 0.961
    R07_P99_S = 0.17

    if args.affinity_only:
        aff = run_affinity_bench(duration_s=args.duration_s)
        _check_affinity_gates(aff)
        ptree = run_prefix_tree_bench(duration_s=args.duration_s)
        _check_prefix_tree_gates(ptree)
        _emit(
            "serving_affinity_hit_rate",
            aff["affine"]["hit_rate"], "fraction",
            max(aff["blind"]["hit_rate"], 1e-9),
            prefix_tree=ptree,
            **aff,
        )
        return

    service_time_s = 0.05
    max_batch = 2
    max_queue = 6
    duration_s = args.duration_s
    capacity_qps = max_batch / service_time_s          # one replica
    rate_qps = 2.0 * capacity_qps                      # 2x overload
    common = dict(
        rate_qps=rate_qps, duration_s=duration_s, replicas=1,
        max_batch=max_batch, max_queue=max_queue,
        service_time_s=service_time_s, client_timeout_s=1.5,
    )

    noshed = run_serve_bench(shed=False, autoscale=False, **common)
    shed = run_serve_bench(shed=True, autoscale=False, **common)
    scaled = run_serve_bench(
        shed=True, autoscale=True, max_replicas=2,
        target_queue_wait_s=service_time_s, scrape_interval_s=0.2,
        **common)

    for tag, rep in (("noshed", noshed), ("shed", shed),
                     ("autoscale", scaled)):
        if not rep["accounting_ok"]:
            raise SystemExit(
                f"serve[{tag}]: accounting broken — offered "
                f"{rep['offered']} != ok {rep['ok']} + shed {rep['shed']} "
                f"+ timeouts {rep['timeouts']} + errors {rep['errors']}"
            )
        if rep["errors"]:
            raise SystemExit(f"serve[{tag}]: {rep['errors']} non-shed "
                             "errors")
        if rep["shed_with_retry_after"] != rep["shed"]:
            raise SystemExit(
                f"serve[{tag}]: {rep['shed'] - rep['shed_with_retry_after']}"
                f" of {rep['shed']} shed responses missing Retry-After"
            )
    for tag, rep in (("shed", shed), ("autoscale", scaled)):
        if rep["timeouts"]:
            raise SystemExit(
                f"serve[{tag}]: {rep['timeouts']} client timeouts with "
                "shedding ON — overload leaked past admission control"
            )
        if rep["goodput_qps"] < 0.7 * capacity_qps:
            raise SystemExit(
                f"serve[{tag}]: goodput {rep['goodput_qps']} qps < 0.7x "
                f"capacity ({capacity_qps} qps) under 2x overload"
            )
    if not noshed["timeouts"]:
        raise SystemExit(
            "serve[noshed]: baseline shows no timeout churn at 2x "
            "overload — the collapse this bench exists to contrast "
            "against did not reproduce (load too low?)"
        )
    if scaled["replicas_end"] != scaled["max_replicas"]:
        raise SystemExit(
            f"serve[autoscale]: stopped at {scaled['replicas_end']}/"
            f"{scaled['max_replicas']} replicas under 2x overload"
        )

    # --- ISSUE 12: continuous batching + paged KV on one KV budget ----
    stepbatch = run_continuous_bench(
        mode="stepbatch", dense_kv=True, duration_s=duration_s)
    cont_dense = run_continuous_bench(
        mode="continuous", dense_kv=True, duration_s=duration_s)
    cont_paged = run_continuous_bench(
        mode="continuous", dense_kv=False, duration_s=duration_s)
    for tag, leg in (("stepbatch", stepbatch),
                     ("continuous-dense", cont_dense),
                     ("continuous-paged", cont_paged)):
        _check_token_leg(tag, leg)
    if stepbatch["midstep_admissions"] != 0:
        raise SystemExit(
            f"serve[stepbatch]: {stepbatch['midstep_admissions']} "
            "mid-step admissions in the step-boundary baseline — the "
            "contrast is contaminated"
        )
    for tag, leg in (("continuous-dense", cont_dense),
                     ("continuous-paged", cont_paged)):
        if leg["midstep_admissions"] == 0:
            raise SystemExit(
                f"serve[{tag}]: zero mid-step admissions — continuous "
                "batching never engaged (vacuous run)"
            )
    paged_g = cont_paged["goodput_vs_dense_capacity"]
    paged_p99 = cont_paged["ttft_ok_s"]["p99"]
    if (paged_g <= stepbatch["goodput_vs_dense_capacity"]
            or paged_p99 >= stepbatch["ttft_ok_s"]["p99"]):
        raise SystemExit(
            f"serve[continuous-paged]: did not beat stepbatch — goodput "
            f"{paged_g} vs {stepbatch['goodput_vs_dense_capacity']}, "
            f"ttft p99 {paged_p99} vs {stepbatch['ttft_ok_s']['p99']}"
        )
    if paged_g <= R07_GOODPUT_VS_CAPACITY or paged_p99 >= R07_P99_S:
        raise SystemExit(
            f"serve[continuous-paged]: did not beat the r07 record — "
            f"goodput {paged_g} (need > {R07_GOODPUT_VS_CAPACITY}), "
            f"ttft p99 {paged_p99} (need < {R07_P99_S})"
        )

    # --- ISSUE 12: cache-affine vs blind routing ----------------------
    aff = run_affinity_bench(duration_s=duration_s)
    _check_affinity_gates(aff)

    # --- ISSUE 13: radix vs exact prefix matching ---------------------
    ptree = run_prefix_tree_bench(duration_s=min(duration_s, 3.0))
    _check_prefix_tree_gates(ptree)

    _emit(
        "serving_overload_goodput_vs_capacity",
        # Headline: the paged continuous plane's goodput on the dense
        # plane's capacity denominator, against the recorded r07 shed
        # leg — what continuous batching + paged KV buy from one KV
        # budget at 2x overload.
        cont_paged["goodput_vs_dense_capacity"],
        "x dense-KV capacity",
        R07_GOODPUT_VS_CAPACITY,
        ttft_p99_s=paged_p99,
        r07_p99_s=R07_P99_S,
        capacity_qps=capacity_qps,
        rate_qps=rate_qps,
        duration_s=duration_s,
        goodput_floor_vs_capacity=0.7,
        noshed=noshed,
        shed=shed,
        autoscale=scaled,
        stepbatch=stepbatch,
        continuous_dense=cont_dense,
        continuous_paged=cont_paged,
        affinity=aff,
        prefix_tree=ptree,
    )


def _check_token_leg(tag: str, leg: dict) -> None:
    """Count gates every ISSUE-12 token leg must clear: exact request
    accounting, honest sheds, zero errors/timeouts, and the KV-block
    conservation invariant (raise, not assert)."""
    if not leg["accounting_ok"]:
        raise SystemExit(
            f"serve[{tag}]: accounting broken — offered {leg['offered']}"
            f" != ok {leg['ok']} + shed {leg['shed']} + timeouts "
            f"{leg['timeouts']} + errors {leg['errors']}"
        )
    if leg["errors"] or leg["timeouts"]:
        raise SystemExit(
            f"serve[{tag}]: errors={leg['errors']} "
            f"timeouts={leg['timeouts']} (must both be 0)"
        )
    if leg["shed_with_retry_after"] != leg["shed"]:
        raise SystemExit(
            f"serve[{tag}]: {leg['shed'] - leg['shed_with_retry_after']} "
            f"of {leg['shed']} sheds missing Retry-After"
        )
    kv = leg["kv"]
    if not kv["conservation_ok"] or kv["blocks_leaked"]:
        raise SystemExit(
            f"serve[{tag}]: KV-block conservation broken — "
            f"conservation_ok={kv['conservation_ok']} "
            f"leaked={kv['blocks_leaked']} "
            f"(allocated {kv['blocks_allocated_total']} freed "
            f"{kv['blocks_freed_total']})"
        )


def _check_prefix_tree_gates(ptree: dict) -> None:
    """The radix-vs-exact prefix-matching A/B's hard gates (ISSUE 13
    satellite) — the one shared contract in
    loadtest.prefix_tree_gate_failures, raised bench-style."""
    from kubeflow_tpu.tools.loadtest import prefix_tree_gate_failures

    failures = prefix_tree_gate_failures(ptree)
    if failures:
        raise SystemExit("; ".join(failures))


def _check_affinity_gates(aff: dict) -> None:
    """The cache-affinity A/B's hard gates: exact accounting and
    conservation in both runs, a count-based hit-rate separation, and
    the hit-rate-driven TTFT separation (p50: the prefill-skip signal —
    tails are queue noise at sub-capacity rates)."""
    for tag in ("affine", "blind"):
        run = aff[tag]
        if not run["accounting_ok"]:
            raise SystemExit(f"affinity[{tag}]: accounting broken: {run}")
        if run["errors"] or run["timeouts"]:
            raise SystemExit(
                f"affinity[{tag}]: errors={run['errors']} "
                f"timeouts={run['timeouts']}")
        if not run["kv_conservation_ok"]:
            raise SystemExit(
                f"affinity[{tag}]: KV-block conservation broken")
    if aff["affine"]["hit_rate"] < aff["blind"]["hit_rate"] + 0.1:
        raise SystemExit(
            f"affinity: hit-rate separation vacuous — affine "
            f"{aff['affine']['hit_rate']} vs blind "
            f"{aff['blind']['hit_rate']} (need >= +0.1)"
        )
    if (aff["affine"]["ttft_ok_s"]["p50"]
            >= aff["blind"]["ttft_ok_s"]["p50"]):
        raise SystemExit(
            f"affinity: no TTFT separation — affine p50 "
            f"{aff['affine']['ttft_ok_s']['p50']} >= blind "
            f"{aff['blind']['ttft_ok_s']['p50']}"
        )


def bench_longctx(args) -> None:
    """Long-context variant of config 2 on ONE chip. Defaults encode the
    MEASURED per-length recipe (BASELINE.md context ladder, 2k→64k):

    - ≤16k: ``qkv_attn_lse`` (saving the flash lse residuals beats
      replaying the S² forward; +4% at 8k)
    - 32k:  ``qkv_attn`` + chunked CE (the lse residuals exceed HBM)
    - 64k:  ``full`` remat + chunked CE (qkv_attn's saved q/k/v ~3 GB +
      replay working set no longer fit; measured OOM)

    Beyond 64k the path is ring/Ulysses sequence parallelism.
    Explicit --remat-policy/--loss-chunk/--batch-size always win
    (--loss-chunk 0 explicitly disables chunking at any length). The
    bare default (--seq-len unset) runs the 8k row; for the 2k config
    use plain ``bench.py`` — longctx treats 2048 as "unset"."""
    args.seq_len = args.seq_len if args.seq_len != 2048 else 8192
    if args.seq_len > 32768:
        # The qkv_attn saves are measured-OOM by 64k; anything past the
        # validated 32k point takes the 64k-safe full-remat recipe.
        args.batch_size = args.batch_size or 1
        args.remat_policy = args.remat_policy or "full"
        if args.loss_chunk is None:
            args.loss_chunk = 4096
    elif args.seq_len > 16384:
        # Between the validated 16k (lse residuals fit) and 32k (measured
        # 1.23G over) points, take the 32k-safe recipe.
        args.batch_size = args.batch_size or 1
        args.remat_policy = args.remat_policy or "qkv_attn"
        if args.loss_chunk is None:
            args.loss_chunk = 8192
    else:
        # Records: 8k = bs3, 16k = bs1 (BASELINE context ladder rows).
        args.batch_size = args.batch_size or (3 if args.seq_len <= 8192
                                              else 1)
        args.remat_policy = args.remat_policy or "qkv_attn_lse"
    bench_train(args)


def bench_sp_crossover(args) -> None:
    """Single-chip kernel proxy for the ring-vs-Ulysses ``sp`` decision
    (parallel/policy.py): time the local attention each scheme runs at its
    per-device shapes. Ring's critical-path device (the last, under causal)
    makes ``sp`` flash calls over S/sp kv blocks + lse merges; Ulysses makes
    one full-length call with H/sp query heads. The a2a / ppermute wire cost
    is not visible single-chip — ring moves ~Hkv/H as many bytes, so the
    kernel proxy is the part that can favour Ulysses at all."""
    import time

    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.ops.flash_attention import (
        NEG_INF, flash_attention, flash_attention_lse,
        merge_attention_blocks,
    )

    B, H, Hkv, D = (args.batch_size or 2), 16, 8, 128
    dtype = jnp.bfloat16
    sp = args.sp
    if H % sp or Hkv % sp:
        raise SystemExit(f"--sp {sp} must divide H={H} and Hkv={Hkv} "
                         "(the proxy models an exact Ulysses head split)")
    bad = [S for S in args.seq_lens if S % sp]
    if bad:
        raise SystemExit(f"--seq-lens {bad} not divisible by --sp {sp}")
    results = []
    for S in args.seq_lens:
        Sq = S // sp
        key = jax.random.PRNGKey(0)
        kq, kk, kv_ = jax.random.split(key, 3)

        def ring_proxy(q, k, v):
            # Device sp-1's causal loop: every kv block is live.
            o = jnp.zeros((B, Sq, H, D), jnp.float32)
            lse = jnp.full((B, H, Sq), NEG_INF, jnp.float32)
            for j in range(sp):
                res = flash_attention_lse(
                    q, k[:, j * Sq:(j + 1) * Sq], v[:, j * Sq:(j + 1) * Sq],
                    causal=True, q_offset=(sp - 1) * Sq, kv_offset=j * Sq,
                )
                assert res is not None, "shapes must be kernel-eligible"
                o, lse = merge_attention_blocks(o, lse, *res)
            return o.astype(dtype)

        def ulysses_proxy(q, k, v):
            return flash_attention(q, k, v, causal=True)

        half = Sq // 2

        def zigzag_proxy(q, k, v):
            # Zigzag ring's per-device schedule is UNIFORM: P+1 half-q
            # flash calls (device 0 shown: lo half once, far half against
            # every kv block). q here is [B, Sq/2, H, D].
            o = jnp.zeros((B, half, H, D), jnp.float32)
            lse = jnp.full((B, H, half), NEG_INF, jnp.float32)
            res = flash_attention_lse(q, k[:, :Sq], v[:, :Sq], causal=True,
                                      q_offset=0, kv_offset=0)
            assert res is not None, "zigzag halves must be kernel-eligible"
            o, lse = merge_attention_blocks(o, lse, *res)
            o2 = jnp.zeros((B, half, H, D), jnp.float32)
            lse2 = jnp.full((B, H, half), NEG_INF, jnp.float32)
            off_far = (2 * sp - 1) * half
            for j in range(sp):
                res = flash_attention_lse(
                    q, k[:, j * Sq:(j + 1) * Sq], v[:, j * Sq:(j + 1) * Sq],
                    causal=True, q_offset=off_far, kv_offset=j * Sq,
                )
                assert res is not None, "zigzag halves must be kernel-eligible"
                o2, lse2 = merge_attention_blocks(o2, lse2, *res)
            # Sum (not concat+slice): both halves must stay live or XLA
            # dead-code-eliminates the far loop entirely.
            return (o + o2).astype(dtype)

        q_r = jax.random.normal(kq, (B, Sq, H, D), dtype)
        k_r = jax.random.normal(kk, (B, S, Hkv, D), dtype)
        v_r = jax.random.normal(kv_, (B, S, Hkv, D), dtype)
        q_u = jax.random.normal(kq, (B, S, H // sp, D), dtype)
        k_u = jax.random.normal(kk, (B, S, Hkv // sp, D), dtype)
        v_u = jax.random.normal(kv_, (B, S, Hkv // sp, D), dtype)
        q_z = jax.random.normal(kq, (B, Sq // 2, H, D), dtype)

        def timed(fn, q0, k0, v0):
            # Per-dispatch tunnel latency (~110 ms) dwarfs these kernels:
            # run `steps` iterations inside ONE jitted dispatch, chained
            # through the q carry so XLA cannot CSE the repeats, and
            # subtract a 0-iteration dispatch to remove the launch floor.
            def repeat(n_iters):
                def run(q, k, v):
                    def body(qc, _):
                        out = fn(qc, k, v)
                        return qc + 1e-6 * out.astype(qc.dtype), None

                    qf, _ = jax.lax.scan(body, q, None, length=n_iters)
                    return jnp.sum(qf, dtype=jnp.float32)

                return jax.jit(run)

            f_n = repeat(args.steps)
            f_0 = repeat(0)
            _sync(f_n(q0, k0, v0)), _sync(f_0(q0, k0, v0))  # compile + warm
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                _sync(f_0(q0, k0, v0))
                t_base = time.perf_counter() - t0
                t0 = time.perf_counter()
                _sync(f_n(q0, k0, v0))
                t_full = time.perf_counter() - t0
                best = min(best, (t_full - t_base) / args.steps)
            return best * 1e3

        ring_ms = timed(ring_proxy, q_r, k_r, v_r)
        uly_ms = timed(ulysses_proxy, q_u, k_u, v_u)
        zz_ms = timed(zigzag_proxy, q_z, k_r, v_r)
        row = {"seq_len": S, "per_device_q": Sq,
               "ring_ms": round(ring_ms, 3),
               "zigzag_ring_ms": round(zz_ms, 3),
               "ulysses_ms": round(uly_ms, 3)}
        if ring_ms > 0 and uly_ms > 0:
            row["ring_over_ulysses"] = round(ring_ms / uly_ms, 3)
        else:
            # Kernel time under the dispatch-jitter floor (short contexts
            # / few --steps): a ratio would be noise, don't report one.
            row["ring_over_ulysses"] = None
            row["noise_floor"] = True
        results.append(row)

    # Headline: the ratio at the longest context. Measured ~1.8-2.9x in
    # Ulysses' favour at every length (causal load skew: ring's last
    # device attends the full rectangle) — hence choose_sp_impl prefers
    # Ulysses whenever its collectives stay exact (see parallel/policy.py).
    valid = [r for r in results if r["ring_over_ulysses"] is not None]
    headline = valid[-1]["ring_over_ulysses"] if valid else 0.0
    _emit("sp_crossover_ring_over_ulysses", headline,
          "x kernel time (last valid ladder row)", 0.0,
          sp=sp, ladder=results)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("which", nargs="?", default="train",
                   choices=["train", "serving", "serving8b", "resnet",
                            "vit", "mixtral", "hpo", "hpo-platform",
                            "controlplane", "serve", "schedule", "longctx",
                            "sp-crossover"])
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--warmup", type=int, default=2)
    # Default is per-bench (train 12, serving 16, resnet 256, vit 64,
    # mixtral 8); an explicit value always wins.
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--attn", default="flash",
                   choices=["full", "flash", "ring", "ulysses"])
    p.add_argument("--requests", type=int, default=None,
                   help="serving requests (default 48) / hpo trials (16) "
                        "/ controlplane jobs (1000) / schedule storm "
                        "jobs (60)")
    p.add_argument("--fleet", default="v5e-16=8",
                   help="schedule bench: fleet spec slice_type=count[,..]")
    p.add_argument("--pool-size", type=int, default=4,
                   help="schedule bench: slices per DCN pool")
    p.add_argument("--seed", type=int, default=1,
                   help="schedule bench: storm seed (arrivals, widths, "
                        "priorities, durations)")
    p.add_argument("--ckpt-every", type=int, default=3,
                   help="schedule bench: checkpoint cadence in productive "
                        "ticks (the goodput ledger's rollback model; 0 = "
                        "continuous checkpointing, no work ever lost)")
    p.add_argument("--goodput-out", default="",
                   help="schedule bench: also write the FIFO-vs-priority "
                        "goodput ledgers (attributed slice-seconds) to "
                        "this JSON file (the GOODPUT_r10.json record)")
    p.add_argument("--elastic", action="store_true",
                   help="schedule bench: run the ELASTIC A/B instead — "
                        "the same seeded storm under capacity "
                        "oscillation twice, elastic resize vs "
                        "restart-only, hard-gated on conservation AND "
                        "elastic beating restart on productive vs "
                        "restart_rollback slice-seconds")
    p.add_argument("--elastic-out", default="",
                   help="schedule --elastic: write the A/B goodput "
                        "ledgers to this JSON file (the ELASTIC_r11.json "
                        "record)")
    p.add_argument("--tenants", action="store_true",
                   help="schedule bench: run the MULTI-TENANT storm A/B "
                        "instead (ISSUE 13) — heavy-tailed per-tenant "
                        "demand + a 10x high-priority burst tenant, "
                        "weighted-DRF enforcement vs raw priority, "
                        "count-gated on ZERO fairness violations under "
                        "enforcement and conservation in both legs")
    p.add_argument("--tenants-out", default="",
                   help="schedule --tenants: write the A/B summaries + "
                        "per-tenant scoreboard to this JSON file (the "
                        "TENANT_r13.json record)")
    p.add_argument("--namespaces", type=int, default=20,
                   help="controlplane bench: namespaces the job fleet is "
                        "spread across (exercises the per-ns index)")
    p.add_argument("--workers", type=int, default=None,
                   help="controlplane bench: reconcile worker-pool size "
                        "(default 1; the --shards baseline defaults to 4); "
                        ">1 runs the scaling sweep (serial vs pool, same "
                        "fleet) gated on final-state equality")
    p.add_argument("--shards", type=int, default=1,
                   help="controlplane bench: shard-process count; >1 runs "
                        "the horizontal scaling sweep (in-process "
                        "workers=4 baseline vs N shard processes, same "
                        "fleet + RTT) hard-gated on cross-shard union "
                        "state-fingerprint equality")
    p.add_argument("--rtt-us", type=int, default=None,
                   help="controlplane --workers sweep: modeled per-verb "
                        "API RTT in microseconds, paid by BOTH runs "
                        "(default 500; 0 = in-process zero-RTT, where the "
                        "GIL — not the dispatcher — is what's measured)")
    p.add_argument("--duration-s", type=float, default=5.0,
                   help="serve bench: open-loop generator duration per "
                        "run (offered = 2x capacity x duration)")
    p.add_argument("--affinity", dest="affinity_only",
                   action="store_true",
                   help="serve bench: run ONLY the cache-affinity A/B "
                        "(affine vs blind routing on the seeded "
                        "session-replay trace)")
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--gen-len", type=int, default=128)
    p.add_argument("--decode-chunk", type=int, default=32)
    p.add_argument("--model", default="llama",
                   choices=["llama", "mixtral"],
                   help="serving bench model family (the engine is "
                        "model-generic)")
    p.add_argument("--max-len", type=int, default=512,
                   help="serving8b engine max_len (KV-cache bound)")
    p.add_argument("--paged", action="store_true",
                   help="serving8b: physically paged KV pool (ISSUE 18) "
                        "— HBM is kv_blocks x kv_block_size rows total "
                        "instead of batch x max_len, breaking the bs112 "
                        "OOM wall and opening 32k max_len on 16G")
    p.add_argument("--kv-blocks", type=int, default=None,
                   help="serving8b --paged: physical pool blocks "
                        "(default: batch x (blocks(prompt+gen) + 1 "
                        "fork-slack))")
    p.add_argument("--kv-block-size", type=int, default=16,
                   help="serving8b --paged: tokens per physical block "
                        "(max_len must divide evenly)")
    p.add_argument("--shared-prefix-len", type=int, default=0,
                   help="serving8b: all prompts open with this many "
                        "common tokens (the prefix-heavy COW leg; "
                        "effective at >= one kv block)")
    p.add_argument("--quantize", default="", choices=["", "int8"],
                   help="serving weight-only quantization")
    p.add_argument("--quantize-kv", default=None, choices=["", "int8"],
                   help="serving KV-cache quantization (halves KV HBM). "
                        "Default int8 for every serving bench — with the "
                        "staged flush it wins on throughput AND TTFT at "
                        "every measured scale; '' selects the bf16 cache")
    p.add_argument("--trace-dir", default="",
                   help="write a jax.profiler trace of the timed steps")
    p.add_argument("--profile", action="store_true",
                   help="train/serving8b: run a second, profiled leg on "
                        "the same compiled fns (obs.profiler phase "
                        "timelines + HBM counters) — hard-gated at <= 2% "
                        "throughput overhead vs the unprofiled control, "
                        "phase/step conservation, and (serving8b) the "
                        ">= 4 phase / >= 2 counter perfetto track floor; "
                        "emits a phase-fraction record")
    # Round-3 measured defaults (decisive same-session sweep, min-of-3):
    # qkv_attn policy (save q/k/v + attention context, replay the MLP)
    # + bf16 Adam mu + bf16 logits beat full remat 55.9% vs 53.4% MFU.
    # Default is per-bench: train qkv_attn (55.9% MFU r3 sweep); mixtral
    # minimal — with the MoE mlp_gate/mlp_up/moe_route tags saved, not
    # replaying the expert block beats the lighter policy (r4: 76.7k vs
    # 73.7k tok/s).
    p.add_argument("--remat-policy", default=None,
                   choices=["none", "full", "minimal", "qkv_attn",
                            "qkv_attn_lse", "attn_only", "mlp_only",
                            "dots"])
    p.add_argument("--mu-dtype", default="bfloat16",
                   help="adam first-moment dtype ('' keeps f32)")
    p.add_argument("--capacity-factor", type=float, default=None,
                   help="MoE expert-buffer capacity factor (default: 1.0 "
                        "for training — the aux balance loss keeps drops "
                        "small; 2.0 for serving, where a static buffer "
                        "overflow silently drops token-expert assignments "
                        "and no loss exists to spread the router)")
    p.add_argument("--loader", default="", choices=["", "native"],
                   help="'native' feeds the C++ ring-buffer pipeline a "
                        "fresh batch per step")
    p.add_argument("--data-path", default="",
                   help="raw int32 token corpus for --loader native "
                        "('' = the loader's synthetic stream)")
    p.add_argument("--moe-dispatch", default="auto",
                   choices=["auto", "gather", "einsum"],
                   help="MoE dispatch mechanism A/B (MixtralConfig)")
    p.add_argument("--arch", default="d64", choices=["d64", "d128"],
                   help="mixtral train bench arch: d64 = config 3; d128 = "
                        "the wider head_dim-128 falsification probe")
    p.add_argument("--sp", type=int, default=8,
                   help="sp-crossover: modeled sequence-parallel extent")
    p.add_argument("--seq-lens", type=int, nargs="+",
                   default=[4096, 8192, 16384, 32768],
                   help="sp-crossover: total context lengths to ladder")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="microbatch gradient accumulation for the train "
                        "bench (TrainConfig.grad_accum_steps)")
    p.add_argument("--loss-chunk", type=int, default=None,
                   help="fuse lm_head+CE blockwise over this many tokens "
                        "(0 = off); frees the [B,S,V] logits buffer")
    p.add_argument("--bf16-logits", dest="bf16_logits", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="emit logits in bf16 (loss still computes f32 stats)")
    p.add_argument("--f32-logits", dest="bf16_logits", action="store_false")
    # bf16 params + f32 Adam moments: the standard TPU mixed-precision
    # recipe — halves param/grad HBM traffic (measured +3% MFU).
    p.add_argument("--param-dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    args = p.parse_args()
    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")
    {
        "train": bench_train,
        "serving": bench_serving,
        "serving8b": bench_serving8b,
        "resnet": bench_resnet,
        "vit": bench_vit,
        "mixtral": bench_mixtral,
        "hpo": bench_hpo,
        "hpo-platform": bench_hpo_platform,
        "controlplane": bench_controlplane,
        "schedule": bench_schedule,
        "serve": bench_serve,
        "longctx": bench_longctx,
        "sp-crossover": bench_sp_crossover,
    }[args.which](args)


if __name__ == "__main__":
    main()
