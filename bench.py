"""Benchmark: flagship Llama training throughput, tokens/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md: `"published": {}`); the
baseline below is the first measurement recorded by this framework at round
1 on a single TPU v5e chip, so vs_baseline tracks our own progress —
BASELINE.md's "to be established, not matched" contract.
"""

from __future__ import annotations

import argparse
import json
import time

# Round-1 reference point (tokens/sec/chip, Llama ~700M, bs8 x seq2048,
# bf16, single v5e chip). Updated when the bench config changes.
BASELINE_TOKENS_PER_SEC = 14500.0


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--attn", default="flash",
                   choices=["full", "flash", "ring", "ulysses"])
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import Llama, LlamaConfig
    from kubeflow_tpu.topology import AxisSpec, make_host_local_mesh
    from kubeflow_tpu.train import TrainConfig, Trainer
    from kubeflow_tpu.train.data import SyntheticTextConfig, synthetic_text
    from kubeflow_tpu.train.flops import (
        device_peak_tflops,
        train_flops_per_token,
    )

    # ~700M-param Llama: big enough that the MXU dominates, small enough
    # for one v5e chip (16G HBM) with f32 Adam state + grads + activations.
    cfg = LlamaConfig(
        vocab_size=32000, embed_dim=2048, num_layers=12, num_heads=16,
        num_kv_heads=8, head_dim=128, mlp_dim=5632,
        max_seq_len=args.seq_len, scan_layers=True, remat=True,
    )
    model = Llama(cfg)
    ndev = len(jax.devices())
    mesh = make_host_local_mesh(AxisSpec(dp=-1))
    trainer = Trainer(
        model,
        TrainConfig(task="lm", warmup_steps=10, total_steps=1000,
                    attn_impl=args.attn),
        mesh,
    )
    it = synthetic_text(
        SyntheticTextConfig(
            batch_size=args.batch_size * ndev,
            seq_len=args.seq_len,
            vocab_size=cfg.vocab_size,
        )
    )
    batch = trainer.shard_batch({k: jnp.asarray(v) for k, v in next(it).items()})
    state = trainer.init_state(jax.random.PRNGKey(0), batch)

    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")
    for _ in range(args.warmup):
        state, metrics = trainer.step(state, batch)
    # Host fetch, not block_until_ready: remote-relay TPU platforms treat
    # block_until_ready as a no-op, so only a device->host transfer is a
    # reliable synchronisation point.
    if args.warmup > 0:
        float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = trainer.step(state, batch)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert final_loss == final_loss, "loss is NaN"

    tokens = args.batch_size * ndev * args.seq_len * args.steps
    tps_chip = tokens / dt / ndev
    flops_per_token = train_flops_per_token(cfg, args.seq_len)
    peak = device_peak_tflops()
    mfu = (
        tps_chip * flops_per_token / (peak * 1e12) if peak > 0 else 0.0
    )
    print(
        json.dumps(
            {
                "metric": "llama_700m_train_tokens_per_sec_per_chip",
                "value": round(tps_chip, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(tps_chip / BASELINE_TOKENS_PER_SEC, 3),
                "mfu": round(mfu, 4),
                "model_tflops_per_chip": round(
                    tps_chip * flops_per_token / 1e12, 2
                ),
                "attn": args.attn,
            }
        )
    )


if __name__ == "__main__":
    main()
