// Native data loader: multi-threaded batch producer with a bounded ring
// buffer, feeding the JAX host-side input pipeline.
//
// The reference's input pipeline is TF's native C++ tier (tf_cnn_benchmarks
// reads via tf.data inside the training image); this is the TPU-native
// equivalent for the framework's own runner/bench: worker threads fill
// pinned int32 token batches from either
//   - a deterministic synthetic stream (splitmix64 per (seed, sample)), or
//   - a memory-mapped binary token file (random crops, epoch-free),
// while the consumer (ctypes, train/native_loader.py) pops complete
// batches without holding the GIL. Throughput goal: keep the host step
// dispatch from ever waiting on data (HBM-bound training must not become
// input-bound).
//
// C ABI only — bound via ctypes (no pybind11 in the image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Config {
  int64_t batch_size;
  int64_t seq_len;
  int64_t vocab_size;
  uint64_t seed;
  int64_t num_threads;
  int64_t queue_depth;
};

// splitmix64: deterministic, splittable — sample i of stream (seed) is a
// pure function, so restarts/replays produce identical data (the same
// contract as data.py's synthetic_text).
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

class Loader {
 public:
  Loader(Config cfg, const char* path, bool validate)
      : cfg_(cfg), stop_(false), produced_(0) {
    if (path != nullptr && path[0] != '\0') {
      int fd = ::open(path, O_RDONLY);
      if (fd >= 0) {
        struct stat st;
        if (::fstat(fd, &st) == 0 && st.st_size >= 0) {
          map_size_ = static_cast<size_t>(st.st_size);
          void* m = ::mmap(nullptr, map_size_, PROT_READ, MAP_PRIVATE, fd, 0);
          if (m != MAP_FAILED) {
            tokens_ = static_cast<const int32_t*>(m);
            n_tokens_ = map_size_ / sizeof(int32_t);
          }
        }
        ::close(fd);
      }
      if (tokens_ == nullptr) {
        error_ = 1;  // surfaced via dl_error
        return;
      }
      if (n_tokens_ < static_cast<uint64_t>(cfg_.seq_len + 1)) {
        error_ = 2;
        return;
      }
      if (validate && cfg_.vocab_size > 0) {
        // Whole-corpus range check at open: an out-of-vocab or corrupt
        // token file must fail loudly, not train on clamped garbage
        // (jnp.take clamps out-of-range indices on TPU). The Python
        // binding caches the verdict per (file, size, mtime, vocab) so a
        // multi-GB corpus is paged through once per host, not once per
        // worker per restart.
        for (uint64_t i = 0; i < n_tokens_; ++i) {
          if (tokens_[i] < 0 || tokens_[i] >= cfg_.vocab_size) {
            error_ = 3;
            return;
          }
        }
      }
    }
    const size_t batch_elems =
        static_cast<size_t>(cfg_.batch_size) * cfg_.seq_len;
    slots_.resize(cfg_.queue_depth);
    for (auto& s : slots_) s.data.resize(batch_elems);
    for (int64_t t = 0; t < cfg_.num_threads; ++t)
      workers_.emplace_back([this] { work(); });
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_free_.notify_all();
    cv_full_.notify_all();
    for (auto& w : workers_) w.join();
    if (tokens_ != nullptr)
      ::munmap(const_cast<int32_t*>(tokens_), map_size_);
  }

  int error() const { return error_; }

  // Blocking pop of the OLDEST ready batch into out (ordered delivery).
  bool next(int32_t* out) {
    std::unique_lock<std::mutex> lk(mu_);
    if (!stop_ && !slot_ready(consume_idx_)) {
      // The training step arrived before the producers: a stall. The
      // bench asserts this stays ~0, proving the pipeline feeds the
      // step rate (BASELINE loader=native row).
      stalls_.fetch_add(1);
    }
    cv_full_.wait(lk, [this] {
      return stop_ || slot_ready(consume_idx_);
    });
    if (stop_) return false;
    Slot& s = slots_[consume_idx_ % slots_.size()];
    std::memcpy(out, s.data.data(), s.data.size() * sizeof(int32_t));
    s.state = kFree;
    ++consume_idx_;
    cv_free_.notify_all();
    return true;
  }

  uint64_t produced() const { return produced_.load(); }

  uint64_t stalls() const { return stalls_.load(); }

 private:
  enum State { kFree = 0, kFilling = 1, kReady = 2 };
  struct Slot {
    std::vector<int32_t> data;
    uint64_t sample_base = 0;
    State state = kFree;
  };

  bool slot_ready(uint64_t idx) {
    return slots_[idx % slots_.size()].state == kReady &&
           slots_[idx % slots_.size()].sample_base ==
               idx * static_cast<uint64_t>(cfg_.batch_size);
  }

  void work() {
    while (true) {
      uint64_t my_batch;
      Slot* slot;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_free_.wait(lk, [this] {
          return stop_ ||
                 slots_[fill_idx_ % slots_.size()].state == kFree;
        });
        if (stop_) return;
        my_batch = fill_idx_++;
        slot = &slots_[my_batch % slots_.size()];
        slot->state = kFilling;
        slot->sample_base =
            my_batch * static_cast<uint64_t>(cfg_.batch_size);
      }
      fill(*slot);
      {
        std::lock_guard<std::mutex> lk(mu_);
        slot->state = kReady;
        produced_.fetch_add(1);
      }
      cv_full_.notify_all();
    }
  }

  void fill(Slot& slot) {
    const int64_t S = cfg_.seq_len;
    for (int64_t b = 0; b < cfg_.batch_size; ++b) {
      const uint64_t sample = slot.sample_base + b;
      int32_t* row = slot.data.data() + b * S;
      if (tokens_ != nullptr) {
        // Random crop, deterministic in (seed, sample).
        const uint64_t span = n_tokens_ - S;
        const uint64_t start = splitmix64(cfg_.seed ^ sample) % span;
        std::memcpy(row, tokens_ + start, S * sizeof(int32_t));
      } else {
        // Synthetic: markov-ish stream with learnable structure (mirrors
        // data.py synthetic_text: next token depends on previous).
        uint64_t state = splitmix64(cfg_.seed ^ (sample * 0x100000001b3ULL));
        int32_t prev = static_cast<int32_t>(state % cfg_.vocab_size);
        for (int64_t i = 0; i < S; ++i) {
          state = splitmix64(state);
          // 75%: deterministic successor (prev*7+3); 25%: random.
          const bool jump = (state & 3) == 0;
          const int32_t succ =
              static_cast<int32_t>((prev * 7 + 3) % cfg_.vocab_size);
          prev = jump ? static_cast<int32_t>((state >> 2) % cfg_.vocab_size)
                      : succ;
          row[i] = prev;
        }
      }
    }
  }

  Config cfg_;
  std::vector<Slot> slots_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_free_, cv_full_;
  bool stop_;
  uint64_t fill_idx_ = 0;
  uint64_t consume_idx_ = 0;
  std::atomic<uint64_t> produced_;
  std::atomic<uint64_t> stalls_{0};
  const int32_t* tokens_ = nullptr;
  uint64_t n_tokens_ = 0;
  size_t map_size_ = 0;
  int error_ = 0;
};

}  // namespace

extern "C" {

void* dl_create(int64_t batch_size, int64_t seq_len, int64_t vocab_size,
                uint64_t seed, int64_t num_threads, int64_t queue_depth,
                const char* token_file, int32_t validate) {
  Config cfg{batch_size, seq_len, vocab_size, seed,
             num_threads > 0 ? num_threads : 2,
             queue_depth > 0 ? queue_depth : 4};
  return new Loader(cfg, token_file, validate != 0);
}

int dl_error(void* h) { return static_cast<Loader*>(h)->error(); }

// Fills out[batch_size * seq_len] int32. Returns 0 on success.
int dl_next(void* h, int32_t* out) {
  return static_cast<Loader*>(h)->next(out) ? 0 : 1;
}

uint64_t dl_produced(void* h) {
  return static_cast<Loader*>(h)->produced();
}

uint64_t dl_stalls(void* h) {
  return static_cast<Loader*>(h)->stalls();
}

void dl_destroy(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
